"""Loss functionals.

Reference analog: python/paddle/nn/functional/loss.py over PHI
softmax_with_cross_entropy etc. cross_entropy keeps paddle's signature
(soft_label, ignore_index, weight, axis).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...ops.registry import register, _ensure_tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "triplet_margin_loss", "ctc_loss", "square_error_cost",
    "sigmoid_focal_loss", "log_loss", "npair_loss", "softmax_cross_entropy_with_logits",
    "multi_label_soft_margin_loss", "soft_margin_loss", "poisson_nll_loss",
    "rnnt_loss", "hsigmoid_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input = _ensure_tensor(input)
    label = _ensure_tensor(label)
    args = [input, label]
    has_w = weight is not None
    if has_w:
        args.append(_ensure_tensor(weight))

    def _f(logits, lab, *w):
        ax = axis % logits.ndim
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=ax)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-15))
        n_class = logits.shape[ax]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            per = -jnp.sum(soft * logp, axis=ax)
            if w:
                cw = jnp.sum(soft * w[0].reshape(
                    [1] * ax + [-1] + [1] * (logits.ndim - ax - 1)), axis=ax)
                per = per * cw
            return _reduce(per, reduction)
        lab_ = lab
        if lab_.ndim == logits.ndim and lab_.shape[ax] == 1:
            lab_ = jnp.squeeze(lab_, axis=ax)
        lab_int = lab_.astype(jnp.int32)
        valid = lab_int != ignore_index
        safe_lab = jnp.where(valid, lab_int, 0)
        if label_smoothing > 0:
            onehot = jax.nn.one_hot(safe_lab, n_class, axis=ax,
                                    dtype=logp.dtype)
            soft = onehot * (1 - label_smoothing) + label_smoothing / n_class
            per = -jnp.sum(soft * logp, axis=ax)
        else:
            per = -jnp.take_along_axis(
                logp, jnp.expand_dims(safe_lab, ax), axis=ax).squeeze(ax)
        per = jnp.where(valid, per, 0.0)
        if w:
            cw = w[0][safe_lab]
            cw = jnp.where(valid, cw, 0.0)
            per = per * cw
            if reduction == "mean":
                return jnp.sum(per) / jnp.maximum(jnp.sum(cw), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1.0)
            return jnp.sum(per) / denom
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as softmax_fn
    from ...tensor.manipulation import unsqueeze
    if not soft_label:
        loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def softmax_cross_entropy_with_logits(logits, labels, axis=-1):
    return cross_entropy(logits, labels, soft_label=True, axis=axis,
                         reduction="none")


def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    input = _ensure_tensor(input)
    label = _ensure_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(_ensure_tensor(weight))

    def _f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        per = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            per = per * w[0]
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    logit = _ensure_tensor(logit)
    label = _ensure_tensor(label)
    args = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        args.append(_ensure_tensor(weight))
    if has_pw:
        args.append(_ensure_tensor(pos_weight))

    def _f(z, y, *rest):
        i = 0
        w = rest[i] if has_w else None
        i += 1 if has_w else 0
        pw = rest[i] if has_pw else None
        max_val = jnp.maximum(-z, 0)
        if pw is not None:
            log_weight = (pw - 1) * y + 1
            per = (1 - y) * z + log_weight * (
                jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val)) + max_val)
        else:
            per = (1 - y) * z + max_val + jnp.log(
                jnp.exp(-max_val) + jnp.exp(-z - max_val))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)
    return apply_op(lambda a, b: _reduce((a - b) ** 2, reduction),
                    input, label, op_name="mse_loss")


def square_error_cost(input, label):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)
    return apply_op(lambda a, b: (a - b) ** 2, input, label,
                    op_name="square_error_cost")


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    input, label, op_name="l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    input = _ensure_tensor(input)
    label = _ensure_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(_ensure_tensor(weight))

    def _f(logp, y, *w):
        y_int = y.astype(jnp.int32)
        valid = y_int != ignore_index
        safe = jnp.where(valid, y_int, 0)
        per = -jnp.take_along_axis(logp, jnp.expand_dims(safe, 1),
                                   axis=1).squeeze(1)
        cw = w[0][safe] if w else jnp.ones_like(per)
        cw = jnp.where(valid, cw, 0.0)
        per = per * cw
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(cw), 1e-12)
        per = jnp.where(valid, per, 0.0)
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="nll_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)

    def _f(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        per = jnp.where(abs_d < delta, 0.5 * d * d / delta,
                        abs_d - 0.5 * delta)
        return _reduce(per, reduction)
    return apply_op(_f, input, label, op_name="smooth_l1_loss")


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)

    def _f(logp, y):
        per = y * (jnp.log(jnp.clip(y, 1e-12)) - logp)
        return _reduce(per, reduction)
    return apply_op(_f, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    input, other = _ensure_tensor(input), _ensure_tensor(other)
    label = _ensure_tensor(label)
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(0, -y * (a - b) + margin),
                                reduction),
        input, other, label, op_name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    input1, input2 = _ensure_tensor(input1), _ensure_tensor(input2)
    label = _ensure_tensor(label)

    def _f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1 - cos, jnp.maximum(0, cos - margin))
        return _reduce(per, reduction)
    return apply_op(_f, input1, input2, label,
                    op_name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",  # noqa: A002
                         name=None):
    input, label = _ensure_tensor(input), _ensure_tensor(label)

    def _f(a, y):
        per = jnp.where(y == 1, a, jnp.maximum(0, margin - a))
        return _reduce(per, reduction)
    return apply_op(_f, input, label, op_name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    input = _ensure_tensor(input)
    positive, negative = _ensure_tensor(positive), _ensure_tensor(negative)

    def _f(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p,
                           axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        per = jnp.maximum(d_pos - d_neg + margin, 0)
        return _reduce(per, reduction)
    return apply_op(_f, input, positive, negative,
                    op_name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the classic alpha-recursion in log space (lax.scan)."""
    log_probs = _ensure_tensor(log_probs)   # [T, B, C] (paddle layout)
    labels = _ensure_tensor(labels)         # [B, S]
    input_lengths = _ensure_tensor(input_lengths)
    label_lengths = _ensure_tensor(label_lengths)

    def _f(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        S = lab.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = -1e30

        alpha0 = jnp.full((B, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same_as_prevprev = jnp.concatenate(
            [jnp.ones((B, 2), dtype=bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prevprev, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new_alpha = merged + emit
            return new_alpha, new_alpha

        _, alphas = lax.scan(step, alpha0, lp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,L]

        t_idx = (in_len.astype(jnp.int32) - 1)
        final = alphas[t_idx, jnp.arange(B)]  # [B, L]
        l_end = 2 * lab_len.astype(jnp.int32)
        p_blank = jnp.take_along_axis(final, l_end[:, None], axis=1)[:, 0]
        p_label = jnp.take_along_axis(
            final, jnp.maximum(l_end - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(p_blank, p_label)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1))
        return _reduce(loss, reduction)
    return apply_op(_f, log_probs, labels, input_lengths, label_lengths,
                    op_name="ctc_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit, label = _ensure_tensor(logit), _ensure_tensor(label)
    args = [logit, label]
    if normalizer is not None:
        args.append(_ensure_tensor(normalizer))

    def _f(z, y, *nz):
        p = lax.logistic(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        per = a_t * ((1 - p_t) ** gamma) * ce
        if nz:
            per = per / nz[0]
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="sigmoid_focal_loss")


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)
    return apply_op(
        lambda p, y: -y * jnp.log(p + epsilon)
        - (1 - y) * jnp.log(1 - p + epsilon),
        input, label, op_name="log_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    anchor, positive = _ensure_tensor(anchor), _ensure_tensor(positive)
    labels = _ensure_tensor(labels)

    def _f(a, p, y):
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        sim = a @ p.T
        y = y.reshape(-1, 1)
        same = (y == y.T).astype(sim.dtype)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(same * logp, axis=1))
        return ce + reg
    return apply_op(_f, anchor, positive, labels, op_name="npair_loss")


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    input, label = _ensure_tensor(input), _ensure_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(_ensure_tensor(weight))

    def _f(z, y, *w):
        per = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        if w:
            per = per * w[0]
        per = jnp.mean(per, axis=-1)
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)
    return apply_op(
        lambda z, y: _reduce(jnp.log1p(jnp.exp(-y * z)), reduction),
        input, label, op_name="soft_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean", name=None):
    input, label = _ensure_tensor(input), _ensure_tensor(label)

    def _f(x, y):
        if log_input:
            per = jnp.exp(x) - y * x
        else:
            per = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y \
                + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            per = per + jnp.where(y > 1, stirling, 0.0)
        return _reduce(per, reduction)
    return apply_op(_f, input, label, op_name="poisson_nll_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,  # noqa: A002
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN transducer loss (Graves 2012).

    Reference analog: the warprnnt-backed op (paddle/phi/kernels/...
    warprnnt; python face paddle.nn.functional.rnnt_loss). TPU-native: the
    alpha recursion runs as a lax.scan over time with the inner
    label-dimension recurrence closed by an associative log-cumsum-exp, so
    the whole DP compiles to one fused loop — no host round trips.

    input: [B, T, U+1, V] logits; label: [B, U] int; lengths per sample.
    """
    from jax import lax as _lax

    input = _ensure_tensor(input)  # noqa: A001
    label = _ensure_tensor(label)
    input_lengths = _ensure_tensor(input_lengths)
    label_lengths = _ensure_tensor(label_lengths)

    def _f(logits, labels, t_lens, u_lens):
        B, T, U1, V = logits.shape
        U = U1 - 1
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        blank_lp = lp[..., blank]                          # [B, T, U+1]
        lab_lp = jnp.take_along_axis(
            lp[:, :, :U, :], labels[:, None, :, None].astype(jnp.int32),
            axis=-1)[..., 0]                               # [B, T, U]
        if fastemit_lambda:
            # FastEmit (Yu et al. 2021), as warprnnt implements it: the
            # loss VALUE is unchanged; gradients through label emissions
            # are scaled by (1 + lambda). value-preserving grad-scale:
            lam = float(fastemit_lambda)
            lab_lp = lab_lp * (1.0 + lam) \
                - jax.lax.stop_gradient(lab_lp * lam)

        def row(alpha_prev, t):
            # alpha_t[u] = logaddexp(alpha_prev[u] + blank_prev[u],
            #                        alpha_t[u-1] + lab[t, u-1])
            # closed form: c[u] = cumsum_pad(lab[t]); alpha_t =
            #   c + logcumsumexp(alpha_prev + blank_prev - c)
            lab_t = lab_lp[:, t, :]                        # [B, U]
            c = jnp.concatenate(
                [jnp.zeros((B, 1), jnp.float32),
                 jnp.cumsum(lab_t, axis=-1)], axis=-1)     # [B, U+1]
            g = alpha_prev + blank_lp[:, t - 1, :] - c
            acc = _lax.associative_scan(jnp.logaddexp, g, axis=-1)
            return c + acc

        # t = 0 row: alpha[0, u] = sum_{j<u} lab[0, j]
        alpha0 = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.float32),
             jnp.cumsum(lab_lp[:, 0, :], axis=-1)], axis=-1)

        def step(alpha, t):
            nxt = row(alpha, t)
            return nxt, alpha

        alpha_T, rows = _lax.scan(step, alpha0,
                                  jnp.arange(1, T))
        all_rows = jnp.concatenate([rows,
                                    alpha_T[None]], axis=0)  # [T, B, U+1]
        all_rows = jnp.moveaxis(all_rows, 0, 1)              # [B, T, U+1]
        tb = jnp.clip(t_lens.astype(jnp.int32) - 1, 0, T - 1)
        ub = jnp.clip(u_lens.astype(jnp.int32), 0, U)
        bidx = jnp.arange(B)
        alpha_end = all_rows[bidx, tb, ub]
        final_blank = blank_lp[bidx, tb, ub]
        per = -(alpha_end + final_blank)
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per

    return apply_op(_f, input, label, input_lengths, label_lengths,
                    op_name="rnnt_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: hsigmoid_loss op /
    python/paddle/nn/functional/loss.py): binary decisions along each
    class's path through a code tree. Custom trees pass
    path_table/path_code; the default is the complete binary tree over
    num_classes leaves (heap numbering), whose paths are derived from
    the labels on the host — call eagerly or precompute tables for jit.
    """
    import numpy as _np

    input = _ensure_tensor(input)  # noqa: A001
    label = _ensure_tensor(label)
    weight = _ensure_tensor(weight)
    if path_table is None or path_code is None:
        lab = _np.asarray(label._array).reshape(-1)
        depth = max(1, int(_np.ceil(_np.log2(max(num_classes, 2)))))
        tables = _np.full((len(lab), depth), -1, _np.int64)
        codes = _np.zeros((len(lab), depth), _np.float32)
        for n, c in enumerate(lab):
            node = int(c) + num_classes
            path = []
            while node > 1:
                path.append((node // 2 - 1, node & 1))
                node //= 2
            for d, (idx, bit) in enumerate(reversed(path)):
                tables[n, d] = idx
                codes[n, d] = bit
        path_table = Tensor(jnp.asarray(tables))
        path_code = Tensor(jnp.asarray(codes))
    else:
        path_table = _ensure_tensor(path_table)
        path_code = _ensure_tensor(path_code)
    args = [input, weight, path_table, path_code]
    if bias is not None:
        args.append(_ensure_tensor(bias))

    def _f(x, w, tbl, code, *b):
        mask = (tbl >= 0).astype(jnp.float32)              # [N, L]
        safe = jnp.clip(tbl, 0, w.shape[0] - 1)
        wrows = w[safe]                                    # [N, L, D]
        z = jnp.einsum("nld,nd->nl", wrows.astype(jnp.float32),
                       x.astype(jnp.float32))
        if b:
            # bias is documented as [num_classes-1, 1] (also accept 1-D)
            z = z + b[0].reshape(-1)[safe]
        # BCE with target = code: softplus(z) - code * z
        per = (jax.nn.softplus(z) - code * z) * mask
        return jnp.sum(per, axis=-1, keepdims=True)

    return apply_op(_f, *args, op_name="hsigmoid_loss")


for _n in __all__:
    register(_n, globals()[_n])


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """reference: nn/functional/loss.py dice_loss — 1 - 2|X∩Y|/(|X|+|Y|)
    over the class probabilities of segmentation logits. input
    [N, ..., C] probabilities; label [N, ..., 1] int."""
    input = _ensure_tensor(input)  # noqa: A001
    label = _ensure_tensor(label)

    def _f(p, y):
        import jax
        num_classes = p.shape[-1]
        oh = jax.nn.one_hot(jnp.squeeze(y, -1), num_classes,
                            dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(oh,
                                                       axis=reduce_dims)
        dice = (2.0 * inter + epsilon) / (union + epsilon)
        return jnp.mean(1.0 - dice)
    return apply_op(_f, input, label, op_name="dice_loss")


def multi_margin_loss(input, label, p=1, margin=1.0,  # noqa: A002
                      weight=None, reduction="mean", name=None):
    """reference: multi_margin_loss — mean_j max(0, margin - x[y] +
    x[j])^p over j != y, per sample."""
    input = _ensure_tensor(input)  # noqa: A001
    label = _ensure_tensor(label)
    args = [input, label] + ([_ensure_tensor(weight)]
                             if weight is not None else [])

    def _f(x, y, *w):
        C = x.shape[-1]
        correct = jnp.take_along_axis(x, y[:, None], axis=-1)
        per = jnp.maximum(0.0, margin - correct + x) ** p
        if w:
            per = per * w[0][y][:, None]
        mask = 1.0 - jax.nn.one_hot(y, C, dtype=x.dtype)
        per = jnp.sum(per * mask, axis=-1) / C
        return _reduce(per, reduction)
    return apply_op(_f, *args, op_name="multi_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference: triplet_margin_with_distance_loss — triplet loss
    with a caller-supplied distance callable (defaults to pairwise
    L2)."""
    input = _ensure_tensor(input)  # noqa: A001
    positive = _ensure_tensor(positive)
    negative = _ensure_tensor(negative)
    if distance_function is None:
        def distance_function(u, v):
            diff = u - v
            diff_arr = getattr(diff, "_array", diff)
            return jnp.sqrt(jnp.maximum(
                jnp.sum(diff_arr * diff_arr, axis=-1), 1e-12))

    def _f(a, pos, neg):
        def dist(u, v):
            d = distance_function(u, v)
            return getattr(d, "_array", d)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        per = jnp.maximum(d_pos - d_neg + margin, 0)
        return _reduce(per, reduction)
    return apply_op(_f, input, positive, negative,
                    op_name="triplet_margin_with_distance_loss")


__all__ += ["dice_loss", "multi_margin_loss",
            "triplet_margin_with_distance_loss"]
for _n in ("dice_loss", "multi_margin_loss",
           "triplet_margin_with_distance_loss"):
    register(_n, globals()[_n])
