"""Pooling functionals.

Reference analog: python/paddle/nn/functional/pooling.py over PHI pool
kernels. TPU-native: lax.reduce_window.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import apply_op
from ...ops.registry import register, _ensure_tensor
from .conv import _tuplize, _pad_cfg

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d",
           "max_unpool2d"]


def _pool(x, kernel, stride, padding, nd, reducer, init, channels_last,
          ceil_mode=False, count_include_pad=True, op_name="pool",
          average=False):
    x = _ensure_tensor(x)
    kernel = _tuplize(kernel, nd)
    stride = _tuplize(stride if stride is not None else kernel, nd)
    pad = _pad_cfg(padding, nd)
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        pad_cfg = [(0, 0)] + list(pad) + [(0, 0)] if channels_last \
            else [(0, 0), (0, 0)] + list(pad)
    if channels_last:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + kernel
        strides = (1, 1) + stride

    def _f(a):
        cfg = pad_cfg
        ceil_extended = False
        if ceil_mode and not isinstance(cfg, str):
            # extend high-side padding so the trailing partial window is
            # kept (reference ceil_mode semantics)
            cfg = list(cfg)
            for ax in range(a.ndim):
                if dims[ax] == 1:
                    continue
                lo, hi = cfg[ax]
                span = a.shape[ax] + lo + hi
                rem = (span - dims[ax]) % strides[ax]
                if rem:
                    cfg[ax] = (lo, hi + strides[ax] - rem)
                    ceil_extended = True
        if average:
            summed = lax.reduce_window(a, 0.0, lax.add, dims, strides,
                                       cfg)
            if not ceil_extended and (
                    count_include_pad or isinstance(cfg, str)
                    or all(p == (0, 0) for p in
                           (pad if not isinstance(pad, str) else []))):
                denom = float(np.prod(kernel))
                return summed / denom
            ones = jnp.ones_like(a)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                       cfg)
            return summed / counts
        return lax.reduce_window(a, init, reducer, dims, strides, cfg)
    return apply_op(_f, x, op_name=op_name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        # ride the 2-D with-index machinery on a dummy width-1 axis:
        # flat indices over L*1 ARE the 1-D positions max_unpool1d eats
        from ...tensor.manipulation import (squeeze, transpose,
                                            unsqueeze)
        nlc = data_format == "NLC"
        xt = _ensure_tensor(x)
        if nlc:
            xt = transpose(xt, [0, 2, 1])
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if stride is None or isinstance(stride, int) \
            else stride[0]
        p = padding if isinstance(padding, int) else padding[0]
        out, idx = _max_pool2d_with_index(
            unsqueeze(xt, -1), (k, 1),
            (k if s is None else s, 1), (p, 0), False, ceil_mode)
        out, idx = squeeze(out, -1), squeeze(idx, -1)
        if nlc:
            out = transpose(out, [0, 2, 1])
            idx = transpose(idx, [0, 2, 1])
        return out, idx
    return _pool(x, kernel_size, stride, padding, 1, lax.max, -jnp.inf,
                 data_format.endswith("C") and data_format != "NCL",
                 ceil_mode, op_name="max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool2d_with_index(x, kernel_size, stride, padding,
                                      data_format == "NHWC", ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, lax.max, -jnp.inf,
                 data_format == "NHWC", ceil_mode, op_name="max_pool2d")


def _max_pool2d_with_index(x, kernel_size, stride, padding, channels_last,
                           ceil_mode=False):
    """max_pool2d(return_mask=True): values + flat argmax index into the
    input H*W plane (reference: max_pool2d_with_index op), the contract
    max_unpool2d consumes."""
    x = _ensure_tensor(x)
    kh, kw = _tuplize(kernel_size, 2)
    sh, sw = _tuplize(stride if stride is not None else kernel_size, 2)
    pad = _tuplize(padding, 2) if not isinstance(padding, (list, tuple)) \
        else tuple(padding)
    ph, pw = (pad if len(pad) == 2 else (pad[0], pad[0]))

    def _f(a):
        if channels_last:
            a = jnp.moveaxis(a, -1, 1)
        N, C, H, W = a.shape
        if ceil_mode:
            OH = -((H + 2 * ph - kh) // -sh) + 1
            OW = -((W + 2 * pw - kw) // -sw) + 1
        else:
            OH = (H + 2 * ph - kh) // sh + 1
            OW = (W + 2 * pw - kw) // sw + 1
        # bottom/right padding may exceed ph/pw under ceil_mode
        eh = (OH - 1) * sh + kh - H - ph
        ew = (OW - 1) * sw + kw - W - pw
        ap = jnp.pad(a, ((0, 0), (0, 0), (ph, max(eh, 0)),
                         (pw, max(ew, 0))),
                     constant_values=-jnp.inf)
        vals, gidx = [], []
        for dy in range(kh):
            for dx in range(kw):
                vals.append(ap[:, :, dy:dy + sh * OH:sh,
                               dx:dx + sw * OW:sw])
                yy = jnp.arange(OH) * sh + dy - ph
                xx = jnp.arange(OW) * sw + dx - pw
                gidx.append(jnp.broadcast_to(yy[:, None] * W + xx[None, :],
                                             (N, C, OH, OW)))
        stack = jnp.stack(vals)
        am = jnp.argmax(stack, axis=0)
        out = jnp.max(stack, axis=0)
        idx = jnp.take_along_axis(jnp.stack(gidx), am[None], axis=0)[0]
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
            idx = jnp.moveaxis(idx, 1, -1)
        return out, idx.astype(jnp.int32)

    return apply_op(_f, x, op_name="max_pool2d_with_index", n_outs=2)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Scatter pooled values back to the positions recorded by
    max_pool2d(return_mask=True) (reference: unpool op)."""
    x = _ensure_tensor(x)
    indices = _ensure_tensor(indices)
    kh, kw = _tuplize(kernel_size, 2)
    sh, sw = _tuplize(stride if stride is not None else kernel_size, 2)
    pad = _tuplize(padding, 2)
    ph, pw = pad
    channels_last = data_format == "NHWC"
    ih, iw = (x.shape[1:3] if channels_last else x.shape[2:4])
    if output_size is None:
        oh = (ih - 1) * sh - 2 * ph + kh
        ow = (iw - 1) * sw - 2 * pw + kw
    else:
        oh, ow = output_size[-2:]

    def _f(a, idx):
        if channels_last:
            a = jnp.moveaxis(a, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        N, C, H, W = a.shape
        flat_v = a.reshape(N, C, H * W)
        flat_i = idx.reshape(N, C, H * W).astype(jnp.int32)

        def scatter(one_v, one_i):
            return jnp.zeros(oh * ow, one_v.dtype).at[one_i].set(one_v)

        out = jax.vmap(jax.vmap(scatter))(flat_v, flat_i)
        out = out.reshape(N, C, oh, ow)
        return jnp.moveaxis(out, 1, -1) if channels_last else out

    return apply_op(_f, x, indices, op_name="max_unpool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool3d_with_index(x, kernel_size, stride, padding,
                                      data_format == "NDHWC", ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, lax.max, -jnp.inf,
                 data_format == "NDHWC", ceil_mode, op_name="max_pool3d")


def _max_pool3d_with_index(x, kernel_size, stride, padding,
                           channels_last, ceil_mode=False):
    """max_pool3d(return_mask=True): values + flat argmax index into
    the input D*H*W volume (max_pool3d_with_index op), the contract
    max_unpool3d consumes."""
    x = _ensure_tensor(x)
    kd, kh, kw = _tuplize(kernel_size, 3)
    sd, sh, sw = _tuplize(stride if stride is not None else kernel_size, 3)
    pd, ph, pw = _tuplize(padding, 3)

    def _f(a):
        if channels_last:
            a = jnp.moveaxis(a, -1, 1)
        N, C, D, H, W = a.shape

        def osz(sz, k, s, p):
            return (-((sz + 2 * p - k) // -s) + 1) if ceil_mode \
                else (sz + 2 * p - k) // s + 1
        OD, OH, OW = osz(D, kd, sd, pd), osz(H, kh, sh, ph), \
            osz(W, kw, sw, pw)
        ed = (OD - 1) * sd + kd - D - pd
        eh = (OH - 1) * sh + kh - H - ph
        ew = (OW - 1) * sw + kw - W - pw
        ap = jnp.pad(a, ((0, 0), (0, 0), (pd, max(ed, 0)),
                         (ph, max(eh, 0)), (pw, max(ew, 0))),
                     constant_values=-jnp.inf)
        vals, gidx = [], []
        for dz in range(kd):
            for dy in range(kh):
                for dx in range(kw):
                    vals.append(ap[:, :, dz:dz + sd * OD:sd,
                                   dy:dy + sh * OH:sh,
                                   dx:dx + sw * OW:sw])
                    zz = jnp.arange(OD) * sd + dz - pd
                    yy = jnp.arange(OH) * sh + dy - ph
                    xx = jnp.arange(OW) * sw + dx - pw
                    flat = (zz[:, None, None] * H + yy[None, :, None]) \
                        * W + xx[None, None, :]
                    gidx.append(jnp.broadcast_to(
                        flat, (N, C, OD, OH, OW)))
        stack = jnp.stack(vals)
        am = jnp.argmax(stack, axis=0)
        out = jnp.max(stack, axis=0)
        idx = jnp.take_along_axis(jnp.stack(gidx), am[None], axis=0)[0]
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
            idx = jnp.moveaxis(idx, 1, -1)
        return out, idx.astype(jnp.int32)

    return apply_op(_f, x, op_name="max_pool3d_with_index", n_outs=2)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, lax.add, 0.0,
                 False, ceil_mode, count_include_pad=not exclusive,
                 op_name="avg_pool1d", average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, lax.add, 0.0,
                 data_format == "NHWC", ceil_mode,
                 count_include_pad=not exclusive, op_name="avg_pool2d",
                 average=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, lax.add, 0.0,
                 data_format == "NDHWC", ceil_mode,
                 count_include_pad=not exclusive, op_name="avg_pool3d",
                 average=True)


def _adaptive_pool(x, output_size, nd, is_max, channels_last, op_name):
    x = _ensure_tensor(x)
    out_sizes = _tuplize(output_size, nd)
    spatial_axes = list(range(1, 1 + nd)) if channels_last \
        else list(range(2, 2 + nd))

    def _f(a):
        out = a
        for i, ax in enumerate(spatial_axes):
            n_in = out.shape[ax]
            n_out = out_sizes[i]
            if n_out is None or n_out == n_in:
                continue
            if n_in % n_out == 0:
                k = n_in // n_out
                new_shape = (out.shape[:ax] + (n_out, k)
                             + out.shape[ax + 1:])
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=ax + 1) if is_max \
                    else jnp.mean(r, axis=ax + 1)
            else:
                # variable-window adaptive pooling (torch-style bounds)
                starts = (np.arange(n_out) * n_in) // n_out
                ends = ((np.arange(n_out) + 1) * n_in + n_out - 1) // n_out
                slices = []
                for s, e in zip(starts, ends):
                    piece = lax.slice_in_dim(out, int(s), int(e), axis=ax)
                    red = jnp.max(piece, axis=ax, keepdims=True) if is_max \
                        else jnp.mean(piece, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out
    return apply_op(_f, x, op_name=op_name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, False, False,
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, False, data_format == "NHWC",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, False, data_format == "NDHWC",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, True, False,
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, True, False,
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, True, False,
                          "adaptive_max_pool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    x = _ensure_tensor(x)
    p = float(norm_type)
    from ...core.tensor import apply_op as _ap
    powed = _ap(lambda a: jnp.abs(a) ** p, x, op_name="lp_pow")
    pooled = avg_pool1d(powed, kernel_size, stride, padding,
                        exclusive=False, ceil_mode=ceil_mode)
    k = kernel_size if isinstance(kernel_size, int) else int(
        np.prod(kernel_size))
    return _ap(lambda a: (a * k) ** (1.0 / p), pooled, op_name="lp_root")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    x = _ensure_tensor(x)
    p = float(norm_type)
    from ...core.tensor import apply_op as _ap
    powed = _ap(lambda a: jnp.abs(a) ** p, x, op_name="lp_pow")
    pooled = avg_pool2d(powed, kernel_size, stride, padding,
                        exclusive=False)
    ks = _tuplize(kernel_size, 2)
    k = int(np.prod(ks))
    return _ap(lambda a: (a * k) ** (1.0 / p), pooled, op_name="lp_root")


for _n in __all__:
    register(_n, globals()[_n])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """1-D unpool: scatter back by the indices max_pool1d(return_mask)
    recorded (reference: unpool op, 1-D form)."""
    if data_format == "NLC":
        from ...tensor.manipulation import transpose
        out = max_unpool1d(transpose(_ensure_tensor(x), [0, 2, 1]),
                           transpose(_ensure_tensor(indices), [0, 2, 1]),
                           kernel_size, stride, padding, "NCL",
                           output_size, name)
        return transpose(out, [0, 2, 1])
    x = _ensure_tensor(x)
    indices = _ensure_tensor(indices)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = k if stride is None else (
        stride if isinstance(stride, int) else stride[0])
    p = padding if isinstance(padding, int) else padding[0]
    il = x.shape[-1]
    ol = output_size[-1] if output_size is not None \
        else (il - 1) * s - 2 * p + k

    def _f(a, idx):
        N, C, L = a.shape

        def scatter(one_v, one_i):
            return jnp.zeros(ol, one_v.dtype).at[one_i].set(one_v)
        return jax.vmap(jax.vmap(scatter))(
            a, idx.astype(jnp.int32)).reshape(N, C, ol)
    return apply_op(_f, x, indices, op_name="max_unpool1d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """3-D unpool: indices are flat positions over the output D*H*W
    (reference: unpool3d op)."""
    if data_format == "NDHWC":
        from ...tensor.manipulation import transpose
        out = max_unpool3d(
            transpose(_ensure_tensor(x), [0, 4, 1, 2, 3]),
            transpose(_ensure_tensor(indices), [0, 4, 1, 2, 3]),
            kernel_size, stride, padding, "NCDHW", output_size, name)
        return transpose(out, [0, 2, 3, 4, 1])
    x = _ensure_tensor(x)
    indices = _ensure_tensor(indices)
    kd, kh, kw = _tuplize(kernel_size, 3)
    sd, sh, sw = _tuplize(stride if stride is not None else kernel_size, 3)
    pd, ph, pw = _tuplize(padding, 3)
    idd, ih, iw = x.shape[2:5]
    if output_size is None:
        od = (idd - 1) * sd - 2 * pd + kd
        oh = (ih - 1) * sh - 2 * ph + kh
        ow = (iw - 1) * sw - 2 * pw + kw
    else:
        od, oh, ow = output_size[-3:]

    def _f(a, idx):
        N, C, D, H, W = a.shape
        flat_v = a.reshape(N, C, D * H * W)
        flat_i = idx.reshape(N, C, D * H * W).astype(jnp.int32)

        def scatter(one_v, one_i):
            return jnp.zeros(od * oh * ow, one_v.dtype).at[one_i].set(one_v)
        out = jax.vmap(jax.vmap(scatter))(flat_v, flat_i)
        return out.reshape(N, C, od, oh, ow)
    return apply_op(_f, x, indices, op_name="max_unpool3d")


__all__ += ["max_unpool1d", "max_unpool3d"]
for _n in ("max_unpool1d", "max_unpool3d"):
    register(_n, globals()[_n])
