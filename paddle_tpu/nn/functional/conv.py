"""Convolution functionals.

Reference analog: python/paddle/nn/functional/conv.py over PHI conv kernels
(gpudnn). TPU-native: lax.conv_general_dilated, which XLA maps onto the MXU
with automatic im2col-free tiling; layouts follow paddle's NCHW/OIHW default
with NHWC accepted (NHWC is the TPU-preferred layout — XLA transposes
internally either way).
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from jax import lax

from ...core.tensor import apply_op
from ...ops.registry import register, _ensure_tensor

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # full-form [[0,0],[0,0],[ph,ph],[pw,pw]] — keep spatial entries
        return [tuple(p) for p in padding[-n:]]
    return [(int(p), int(p)) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
          nd, op_name):
    x = _ensure_tensor(x)
    weight = _ensure_tensor(weight)
    stride = _tuplize(stride, nd)
    dilation = _tuplize(dilation, nd)
    pad = _pad_cfg(padding, nd)
    channels_last = data_format.endswith("C")
    sp = "DHW"[3 - nd:]
    if channels_last:
        dn_str = ("N" + sp + "C", "O" + sp + "I", "N" + sp + "C")
    else:
        dn_str = ("NC" + sp, "OI" + sp, "NC" + sp)
    # paddle weights are always OI<sp> regardless of data_format
    dn_lhs = dn_str[0]
    dn = lax.conv_dimension_numbers((1,) * (nd + 2), weight._array.shape,
                                    (dn_lhs, "OI" + sp, dn_lhs))

    args = [x, weight]
    if bias is not None:
        args.append(_ensure_tensor(bias))

    def _f(a, w, b=None):
        out = lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=dn)
        if b is not None:
            shape = [1] * out.ndim
            shape[-1 if channels_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    return apply_op(_f, *args, op_name=op_name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCH" if data_format in ("NCL", "NCH") else "NHC"
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 fmt, 1, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 2, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format, nd, op_name,
                    output_size=None):
    x = _ensure_tensor(x)
    weight = _ensure_tensor(weight)
    stride = _tuplize(stride, nd)
    dilation = _tuplize(dilation, nd)
    outpad = _tuplize(output_padding, nd)
    channels_last = data_format.endswith("C")
    sp = "DHW"[3 - nd:]
    dn_lhs = ("N" + sp + "C") if channels_last else ("NC" + sp)
    dn = lax.conv_dimension_numbers((1,) * (nd + 2), weight._array.shape,
                                    (dn_lhs, "IO" + sp, dn_lhs))
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        base = _pad_cfg(padding, nd)
        # transpose conv: effective padding = k_eff - 1 - p
        ks = weight._array.shape[2:]
        pad = []
        for i in range(nd):
            k_eff = (ks[i] - 1) * dilation[i] + 1
            lo = k_eff - 1 - base[i][0]
            hi = k_eff - 1 - base[i][1] + outpad[i]
            pad.append((lo, hi))

    args = [x, weight]
    if bias is not None:
        args.append(_ensure_tensor(bias))

    def _f(a, w, b=None):
        out = lax.conv_general_dilated(
            a, w, window_strides=(1,) * nd, padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups, dimension_numbers=dn)
        if b is not None:
            shape = [1] * out.ndim
            shape[-1 if channels_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    return apply_op(_f, *args, op_name=op_name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    fmt = "NCH" if data_format in ("NCL", "NCH") else "NHC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, fmt, 1, "conv1d_transpose",
                           output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2,
                           "conv2d_transpose", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3,
                           "conv3d_transpose", output_size)


for _n in __all__:
    register(_n, globals()[_n])
