"""Normalization functionals.

Reference analog: python/paddle/nn/functional/norm.py over PHI
batch_norm/layer_norm kernels (paddle/phi/kernels/gpu/layer_norm_kernel.cu
etc.). XLA fuses the mean/var/normalize chain; rms_norm is the TPU-era
addition (reference lacks it — PaddleNLP-era op).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...ops.registry import register, _ensure_tensor

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "normalize", "rms_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    x = _ensure_tensor(x)
    ch_axis = x.ndim - 1 if data_format.endswith("C") and x.ndim > 2 else 1
    if x.ndim == 2:
        ch_axis = 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    args = [x]
    for t in (weight, bias):
        if t is not None:
            args.append(_ensure_tensor(t))
    has_w = weight is not None
    has_b = bias is not None

    # running stats travel as op INPUTS (not closure constants) so a
    # recorded static program reads their LIVE values on every replay
    rm_t = running_mean if isinstance(running_mean, Tensor) \
        else Tensor(jnp.asarray(running_mean))
    rv_t = running_var if isinstance(running_var, Tensor) \
        else Tensor(jnp.asarray(running_var))
    args += [rm_t, rv_t]

    def _f(a, *rest):
        i = 0
        w = rest[i] if has_w else None
        i += 1 if has_w else 0
        b = rest[i] if has_b else None
        i += 1 if has_b else 0
        rm, rv = rest[i], rest[i + 1]
        if use_batch_stats:
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
        else:
            mean, var = rm, rv
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a - mean.reshape(shape)) * lax.rsqrt(
            var.reshape(shape) + epsilon)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out, mean, var
    out, batch_mean, batch_var = apply_op(_f, *args, op_name="batch_norm")

    # update running stats in place (matches reference's in-place update);
    # works under trace too — the new stats become traced values the caller's
    # functional step can return. Stats are the ones computed inside _f,
    # not a second reduction over x. The updates themselves go through
    # apply_op so static programs record them; record_state_write makes
    # the Executor persist them into the live buffers each run.
    if use_batch_stats and isinstance(running_mean, Tensor):
        def _upd_var(v, bv, a):
            # unbiased correction from the RUN-time batch (a.shape is the
            # fed shape under the per-signature static replay, not the
            # build placeholder's)
            n = 1
            for ax in reduce_axes:
                n *= a.shape[ax]
            return momentum * v + (1 - momentum) * (bv * (n / max(n - 1, 1)))

        new_mean = apply_op(
            lambda m, bm: momentum * m + (1 - momentum) * bm,
            rm_t, batch_mean, op_name="bn_update_mean")
        new_var = apply_op(_upd_var, rv_t, batch_var, x,
                           op_name="bn_update_var")
        from ...static.program import record_state_write, recording_program
        if recording_program() is None:
            # eager: apply in place, the reference's semantics
            running_mean._set_array(new_mean._array)
            running_var._set_array(new_var._array)
        else:
            # recording: the build runs on placeholder zeros — mutating
            # the live buffers now would decay real (checkpoint-loaded)
            # stats; the Executor persists the replayed values instead
            record_state_write(running_mean, new_mean)
            record_state_write(running_var, new_var)
    return out


import jax  # noqa: E402


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = _ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(list(normalized_shape))
    axes = tuple(range(x.ndim - nd, x.ndim))

    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_ensure_tensor(weight))
    if has_b:
        args.append(_ensure_tensor(bias))

    def _f(a, *wb):
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * lax.rsqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out
    return apply_op(_f, *args, op_name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-06, name=None):
    """RMSNorm (Llama-family). Not in the reference snapshot; included as a
    first-class op because it is the dominant norm for the LLM configs in
    BASELINE.json."""
    x = _ensure_tensor(x)
    args = [x]
    if weight is not None:
        args.append(_ensure_tensor(weight))

    def _f(a, *w):
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        out = (a32 * lax.rsqrt(ms + epsilon)).astype(dt)
        if w:
            out = out * w[0]
        return out
    return apply_op(_f, *args, op_name="rms_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  eps=1e-05, data_format="NCHW", name=None):
    x = _ensure_tensor(x)
    axes = tuple(range(2, x.ndim))
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_ensure_tensor(weight))
    if has_b:
        args.append(_ensure_tensor(bias))

    def _f(a, *wb):
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * lax.rsqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out
    return apply_op(_f, *args, op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _ensure_tensor(x)
    channels_last = data_format.endswith("C") and data_format != "NCHW" \
        and data_format != "NCDHW"
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(_ensure_tensor(weight))
    if has_b:
        args.append(_ensure_tensor(bias))

    def _f(a, *wb):
        if channels_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = num_groups
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped, axis=axes, keepdims=True)
        var = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - mean) * lax.rsqrt(var + epsilon)).reshape(a_t.shape)
        shape = [1, c] + [1] * (a_t.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op(_f, *args, op_name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = _ensure_tensor(x)
    ch_axis = x.ndim - 1 if data_format.endswith("C") else 1

    def _f(a):
        sq = a * a
        c = a.shape[ch_axis]
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[ch_axis] = (half, size - 1 - half)
        padded = jnp.pad(sq, pads)
        windows = sum(lax.slice_in_dim(padded, i, i + c, axis=ch_axis)
                      for i in range(size))
        div = (k + alpha / size * windows) ** beta
        return a / div
    return apply_op(_f, x, op_name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply_op(_f, x, op_name="normalize")


for _n in __all__:
    register(_n, globals()[_n])
