"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference analog: python/paddle/nn/decode.py (BeamSearchDecoder over
RNN cells, dynamic_decode's step loop with finished tracking and
parent-id backtracking).

TPU-native note: this is the CELL-level decoding API for seq2seq RNN
models, run as a host-stepped loop (states are tiny; per-step
collectives don't exist here). LLM generation takes the other path —
models/decoding.py compiles the whole KV-cache decode loop into one
``lax.scan``. Both are first-class; they serve different model
families, exactly as the reference splits nn.decode from
fused_multi_transformer generation.
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


def _arr(x):
    return getattr(x, "_array", x)


def _map_states(fn, states):
    return jax.tree_util.tree_map(
        lambda a: fn(_arr(a)), states,
        is_leaf=lambda x: isinstance(x, (Tensor, jnp.ndarray, np.ndarray)))


class BeamSearchDecoder:
    """Beam search over a step cell (reference: decode.py:33).

    cell(inputs, states) -> (outputs, new_states); ``embedding_fn``
    maps token ids to cell inputs; ``output_fn`` maps cell outputs to
    vocabulary logits (identity when the cell already emits logits).
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers ------------------------------------------------------------
    def _tile(self, a):
        """[B, ...] -> [B*beam, ...] (tile_beam_merge_with_batch)."""
        a = _arr(a)
        return jnp.repeat(a, self.beam_size, axis=0)

    tile_beam_merge_with_batch = _tile

    def initialize(self, initial_cell_states):
        states = _map_states(self._tile, initial_cell_states)
        # beam 0 live, others -inf: the first expansion must not pick
        # `beam_size` copies of the same token
        return states

    def _logits_of(self, cell_out):
        out = cell_out[0] if isinstance(cell_out, (tuple, list)) \
            else cell_out
        if self.output_fn is not None:
            out = self.output_fn(out)
        return _arr(out)

    def step(self, tokens, states):
        """One expansion: tokens [B*beam] -> (log_probs [B*beam, V],
        new_states)."""
        inputs = tokens
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(tokens)
        inputs_t = inputs if isinstance(inputs, Tensor) \
            else Tensor(jnp.asarray(_arr(inputs)))
        states_t = _map_states(lambda a: Tensor(a), states)
        out = self.cell(inputs_t, states_t)
        cell_out, new_states = out if isinstance(out, tuple) and \
            len(out) == 2 else (out, states_t)
        logits = self._logits_of(cell_out)
        new_states = _map_states(lambda a: a, new_states)
        return jax.nn.log_softmax(logits, axis=-1), new_states


def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: int = 100, output_time_major: bool = False,
                   impute_finished: bool = True, is_test: bool = False,
                   return_length: bool = False, **kwargs):
    """Run the decoder to completion (reference: decode.py:605
    dynamic_decode): expand beams until every beam emitted end_token or
    ``max_step_num`` steps elapsed, then backtrack parent ids into
    final token sequences.

    Returns (predicted_ids, sequence_lengths) with predicted_ids
    [B, T, beam] (or [T, B, beam] when ``output_time_major``), beams
    sorted best-first by accumulated log-prob.
    """
    beam = decoder.beam_size
    states = decoder.initialize(inits)
    leaves = jax.tree_util.tree_leaves(states)
    if not leaves:
        raise ValueError("dynamic_decode needs initial cell states "
                         "(pass inits=cell.get_initial_states(...))")
    B = int(np.asarray(_arr(leaves[0])).shape[0]) // beam

    tokens = jnp.full((B * beam,), decoder.start_token, jnp.int32)
    scores = jnp.where(jnp.arange(B * beam) % beam == 0, 0.0, -np.inf)
    finished = jnp.zeros((B * beam,), bool)
    step_tokens, step_parents = [], []
    lengths = jnp.zeros((B * beam,), jnp.int32)

    for t in range(int(max_step_num)):
        log_probs, new_states = decoder.step(tokens, states)
        V = log_probs.shape[-1]
        # finished beams only extend with end_token at zero cost
        fin_row = jnp.full((V,), -np.inf).at[decoder.end_token].set(0.0)
        log_probs = jnp.where(finished[:, None], fin_row, log_probs)
        cand = scores[:, None] + log_probs              # [B*beam, V]
        cand = cand.reshape(B, beam * V)
        top_v, top_i = jax.lax.top_k(cand, beam)        # [B, beam]
        parent = top_i // V                             # beam index
        tok = (top_i % V).astype(jnp.int32)
        # flat gather indices into the expanded batch
        gather = (jnp.arange(B)[:, None] * beam + parent).reshape(-1)
        states = _map_states(lambda a: a[gather], new_states)
        prev_finished = finished[gather]
        tokens = tok.reshape(-1)
        scores = top_v.reshape(-1)
        lengths = jnp.where(prev_finished, lengths[gather],
                            lengths[gather] + 1)
        finished = prev_finished | (tokens == decoder.end_token)
        step_tokens.append(tokens.reshape(B, beam))
        step_parents.append(parent)
        # early-exit: the all-finished check is a device-side reduction
        # dispatched with the rest of the step's async work; the host
        # reads exactly ONE scalar per step via an explicit device_get
        # (tpu_lint host-sync-in-loop: no implicit bool(jnp.all(...))
        # blocking the dispatch queue mid-step)
        all_done = jnp.all(finished)
        if bool(jax.device_get(all_done)):
            break

    # backtrack parent ids (reference: gather_tree)
    T = len(step_tokens)
    ids = np.zeros((B, T, beam), np.int32)
    cur = np.tile(np.arange(beam), (B, 1))
    for t in range(T - 1, -1, -1):
        ids[:, t, :] = np.take_along_axis(
            np.asarray(step_tokens[t]), cur, axis=1)
        cur = np.take_along_axis(np.asarray(step_parents[t]), cur, axis=1)

    if impute_finished:
        # replace everything after each beam's first end_token with it
        done = np.cumsum(ids == decoder.end_token, axis=1) > 0
        shifted = np.roll(done, 1, axis=1)
        shifted[:, 0, :] = False
        ids = np.where(shifted, decoder.end_token, ids)

    seq_len = Tensor(lengths.reshape(B, beam))
    out = np.transpose(ids, (1, 0, 2)) if output_time_major else ids
    return Tensor(jnp.asarray(out)), seq_len
