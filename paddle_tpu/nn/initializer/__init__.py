"""Weight initializers.

Reference analog: python/paddle/nn/initializer/ (Constant/Normal/Uniform/
Xavier/Kaiming/TruncatedNormal/Orthogonal/Assign/Dirac) backed there by
fill-op programs; here each initializer is a pure function
(shape, dtype) -> jnp array drawn from the global Generator's keys.
"""
from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.random import next_key

__all__ = [
    "Bilinear",
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
]

_GLOBAL = {"weight": None, "bias": None}


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.normal(next_key(), tuple(shape),
                                  dtype=jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        z = jax.random.truncated_normal(next_key(), self.a, self.b,
                                        tuple(shape), dtype=jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_key(), tuple(shape), dtype=jnp.float32,
                                  minval=self.low,
                                  maxval=self.high).astype(dtype)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    else:
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        # paddle convention: shape[0]=fan_in-ish for Linear ([in,out]),
        # conv weights are [out_c, in_c, *k]
        if len(shape) == 2:
            fan_in, fan_out = shape[0], shape[1]
        else:
            fan_in = shape[1] * receptive
            fan_out = shape[0] * receptive
    return fan_in, fan_out


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * _math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(next_key(), tuple(shape),
                                  dtype=jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * _math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / _math.sqrt(fi)
        return (jax.random.normal(next_key(), tuple(shape),
                                  dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * _math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._array
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign initializer shape mismatch {arr.shape} vs {shape}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        init = jax.nn.initializers.orthogonal(scale=self.gain)
        return init(next_key(), tuple(shape), jnp.float32).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out_c, in_c = shape[0], shape[1]
        arr = np.zeros(shape, dtype=np.float32)
        centers = [k // 2 for k in shape[2:]]
        per = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per, in_c)):
                idx = (g * per + i, i) + tuple(centers)
                arr[idx] = 1.0
        return jnp.asarray(arr, dtype=dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return _math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        slope = param if param is not None else 0.01
        return _math.sqrt(2.0 / (1 + slope ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(f"unsupported nonlinearity {nonlinearity}")


def set_global_initializer(weight_init, bias_init=None):
    _GLOBAL["weight"] = weight_init
    _GLOBAL["bias"] = bias_init


def _resolve_initializer(attr, default_initializer=None, is_bias=False):
    """ParamAttr/initializer resolution (fluid.initializer analog)."""
    from ...framework.param_attr import ParamAttr
    if isinstance(attr, Initializer):
        return attr
    if isinstance(attr, ParamAttr) and attr.initializer is not None:
        return attr.initializer
    if default_initializer is not None:
        return default_initializer
    g = _GLOBAL["bias" if is_bias else "weight"]
    if g is not None:
        return g
    return Constant(0.0) if is_bias else XavierUniform()


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed conv weights
    [C_out, C_in, K, K] — every (out, in) filter gets the kernel, as in
    the reference (python/paddle/nn/initializer/Bilinear over
    fluid/initializer.py BilinearInitializer; typical use is
    Conv2DTranspose with groups=C and weight [C, 1, K, K])."""

    def __call__(self, shape, dtype):
        assert len(shape) == 4, "Bilinear expects a 4-D conv weight"
        k = shape[-1]
        assert shape[-2] == k, "Bilinear expects square kernels"
        # Caffe/paddle formula: f = ceil(k/2), c = (2f - 1 - f%2) / (2f),
        # w[i] = 1 - |i/f - c|
        f = (k + 1) // 2
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = jnp.arange(k, dtype=jnp.float32)
        filt = 1.0 - jnp.abs(og / f - c)
        kernel2d = filt[:, None] * filt[None, :]
        return jnp.broadcast_to(kernel2d, shape).astype(dtype)
