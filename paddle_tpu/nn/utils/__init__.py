"""nn.utils: weight_norm/spectral_norm wrappers, parameter flattening.

Reference analog: python/paddle/nn/utils/.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ..layer.layers import Parameter

__all__ = ["parameters_to_vector", "vector_to_parameters", "weight_norm",
           "remove_weight_norm", "spectral_norm"]


def parameters_to_vector(parameters, name=None):
    arrs = [p._array.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrs))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    arr = vec._array if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = int(np.prod(p._array.shape)) if p._array.shape else 1
        p._set_array(arr[offset:offset + n].reshape(p._array.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| via a forward-pre-hook."""
    weight = getattr(layer, name)
    w = weight._array
    if dim is None:
        norm = jnp.linalg.norm(w)
    else:
        axes = tuple(i for i in range(w.ndim) if i != dim)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))
    g = Parameter(norm)
    v = Parameter(w)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def compute(lyr):
        vv = getattr(lyr, name + "_v")._array
        gg = getattr(lyr, name + "_g")._array
        if dim is None:
            nrm = jnp.linalg.norm(vv)
        else:
            axes = tuple(i for i in range(vv.ndim) if i != dim)
            nrm = jnp.sqrt(jnp.sum(vv * vv, axis=axes, keepdims=True))
        w_t = Tensor(gg * vv / jnp.maximum(nrm, 1e-12))
        w_t.stop_gradient = False
        object.__setattr__(lyr, name, w_t)

    def hook(lyr, inputs):
        compute(lyr)
        return None
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    layer._weight_norm_name = name
    compute(layer)
    return layer


def remove_weight_norm(layer, name="weight"):
    if hasattr(layer, "_weight_norm_handle"):
        layer._weight_norm_handle.remove()
    g = layer._parameters.pop(name + "_g", None)
    v = layer._parameters.pop(name + "_v", None)
    if v is not None:
        w = getattr(layer, name)
        p = Parameter(w._array if isinstance(w, Tensor) else v._array)
        layer.add_parameter(name, p)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from ..layer.norm import SpectralNorm as _SN
    weight = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(weight.shape, dim=dim, power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = Parameter(weight._array)
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]

    def hook(lyr, inputs):
        w = sn(getattr(lyr, name + "_orig"))
        object.__setattr__(lyr, name, w)
        return None
    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer
