"""Norm layers.

Reference analog: python/paddle/nn/layer/norm.py (_BatchNormBase,
LayerNorm, GroupNorm, InstanceNorm*D, SpectralNorm). RMSNorm added as
first-class (LLM configs).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .layers import Layer
from .. import functional as F
from .. import initializer as I
from ...core.tensor import Tensor

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "GroupNorm",
           "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
           "LocalResponseNorm", "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW"
                         else data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm: under pjit the batch axis is sharded and the
    mean/var reductions auto-become psums over the data axis (GSPMD). The
    explicit-collective variant lives in distributed/sync_bn for shard_map
    code. convert_sync_batchnorm mirrors the reference API."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            setattr(out, name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral normalization via power iteration
    (reference: nn/layer/norm.py SpectralNorm over spectral_norm op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.tensor import apply_op
        u0, v0 = self.weight_u._array, self.weight_v._array
        dim, power_iters, eps = self._dim, self._power_iters, self._eps

        def _f(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            # power iteration refines the persistent u/v estimate; gradients
            # do not flow through it (reference treats U/V as buffers)
            u, v = u0, v0
            for _ in range(power_iters):
                v = lax.stop_gradient(wm).T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = lax.stop_gradient(wm) @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma, u, v
        out, u_new, v_new = apply_op(_f, weight, op_name="spectral_norm")
        # persist the refined vectors so sigma converges across forwards
        self.weight_u._set_array(u_new._array)
        self.weight_v._set_array(v_new._array)
        return out
