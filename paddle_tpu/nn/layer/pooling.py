"""Pooling layers.

Reference analog: python/paddle/nn/layer/pooling.py.
"""
from __future__ import annotations

from .layers import Layer
from .. import functional as F

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kw = kw


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kw.items()
                               if k in ("ceil_mode", "data_format")})


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kw.items()
                               if k in ("ceil_mode", "data_format")})


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kw.items()
                               if k in ("ceil_mode", "data_format")})


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kw.items()
                               if k in ("exclusive", "ceil_mode",
                                        "data_format")})


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kw.items()
                               if k in ("exclusive", "ceil_mode",
                                        "data_format")})


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kw.items()
                               if k in ("exclusive", "ceil_mode",
                                        "data_format")})


class _AdaptivePoolNd(Layer):
    def __init__(self, output_size, **kw):
        super().__init__()
        self.output_size = output_size
        self.kw = kw


class AdaptiveAvgPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     self.kw.get("data_format", "NCHW"))


class AdaptiveAvgPool3D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     self.kw.get("data_format", "NCDHW"))


class AdaptiveMaxPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format,
                      output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return F.max_unpool1d(x, indices, k, s, p, df, osz)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format,
                      output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return F.max_unpool2d(x, indices, k, s, p, df, osz)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format,
                      output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return F.max_unpool3d(x, indices, k, s, p, df, osz)


__all__ += ["MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D"]
