"""Layer — the module base class.

Reference analog: python/paddle/fluid/dygraph/layers.py (class Layer):
parameter/buffer/sublayer registries routed through __setattr__, state_dict
with dotted structured names, train/eval recursion, forward pre/post hooks,
create_parameter via ParamAttr + initializer. The TPU-native addition is
`raw_dict()`/`load_raw_dict()` which expose the parameters as a jax pytree
for jit-compiled functional train steps.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core import dtype as dtype_mod
from ...framework.param_attr import ParamAttr
from ..initializer import Constant, XavierUniform, _resolve_initializer

__all__ = ["Layer", "Parameter", "Sequential", "LayerList", "ParameterList",
           "LayerDict"]


class Parameter(Tensor):
    """Trainable leaf tensor (reference: EagerParamBase,
    python/paddle/fluid/framework.py)."""

    def __init__(self, array, trainable=True, name=""):
        super().__init__(array, stop_gradient=not trainable, name=name)
        self.is_leaf_param = True
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._array,), (p.stop_gradient,)),
    lambda aux, ch: Tensor(ch[0], stop_gradient=aux[0]))

_name_counters: Dict[str, int] = collections.defaultdict(int)


def _unique_name(prefix):
    _name_counters[prefix] += 1
    return f"{prefix}_{_name_counters[prefix] - 1}"


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._full_name = _unique_name(
            name_scope or type(self).__name__.lower())

    # -- naming -----------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- parameter creation ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dt = dtype_mod.convert_dtype(dtype) or self._dtype
        init = _resolve_initializer(attr, default_initializer, is_bias)
        arr = init([int(s) for s in shape], dt)
        p = Parameter(arr, trainable=attr.trainable)
        p.name = attr.name or _unique_name("param")
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return Tensor(jnp.zeros([0], dtype_mod.convert_dtype(dtype)
                                or self._dtype))

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                del params[name]
            if layers is not None and name in layers and value is None:
                del layers[name]
            if buffers is not None and name in buffers \
                    and isinstance(value, (Tensor, type(None))):
                buffers[name] = value
            object.__setattr__(self, name, value)

    def add_sublayer(self, name, sublayer):
        if sublayer is not None:
            self._sub_layers[str(name)] = sublayer
            object.__setattr__(self, str(name), sublayer)
        return sublayer

    def add_parameter(self, name, parameter):
        if parameter is not None:
            self._parameters[str(name)] = parameter
            object.__setattr__(self, str(name), parameter)
        return parameter

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[str(name)] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        object.__setattr__(self, str(name), tensor)
        return tensor

    # -- traversal ---------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self.named_children():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lp}.{name}" if lp else name), p

    def functional_forward(self, param_arrays, *input_arrays, **kwargs):
        """Run forward() with parameters substituted by `param_arrays`
        (same order as self.parameters()), on raw jax arrays, returning
        raw arrays. Pure in the arrays — the bridge that lets eager
        Layers run under vmap/scan/jit (e.g. batched MoE experts)."""
        from ...core.tensor import no_grad
        params = self.parameters()
        if len(param_arrays) != len(params):
            raise ValueError(
                f"expected {len(params)} param arrays, got "
                f"{len(param_arrays)}")
        old = [p._array for p in params]
        try:
            for p, a in zip(params, param_arrays):
                p._array = a
            with no_grad():
                out = self.forward(*[Tensor(a) for a in input_arrays],
                                   **kwargs)
            if isinstance(out, (tuple, list)):
                return type(out)(o._array if isinstance(o, Tensor) else o
                                 for o in out)
            return out._array if isinstance(out, Tensor) else out
        finally:
            for p, o in zip(params, old):
                p._array = o

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}.{name}" if lp else name), b

    # -- mode --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                p._set_array(p._array.astype(dt))
            for b in self.buffers():
                if b is not None and dtype_mod.is_floating_point(b.dtype):
                    b._set_array(b._array.astype(dt))
            for l in self.sublayers(include_self=True):
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None \
            else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix,
                                          include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            dest[name] = b
        # drop non-persistable buffers
        for lp, layer in self.named_sublayers(include_self=True):
            for bname in layer._non_persistable_buffer_names:
                key = f"{lp}.{bname}" if lp else bname
                if structured_name_prefix:
                    key = f"{structured_name_prefix}{key}"
                dest.pop(key, None)
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            arr = v._array if isinstance(v, Tensor) else jnp.asarray(
                np.asarray(v))
            if tuple(arr.shape) != tuple(target._array.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {arr.shape} vs "
                    f"{target._array.shape}")
            target._set_array(arr.astype(target._array.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- functional bridge (TPU-native) -----------------------------------
    def raw_dict(self):
        """state_dict as a flat {name: jax.Array} pytree for jit steps."""
        return {k: v._array for k, v in self.state_dict().items()}

    def load_raw_dict(self, raw):
        sd = self.state_dict()
        for k, arr in raw.items():
            if k in sd:
                sd[k]._set_array(arr)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            mod_str = repr(l)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = type(self).__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def extra_repr(self):
        return ""


class Sequential(Layer):
    """Reference: python/paddle/nn/layer/container.py Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, item in enumerate(layers):
                if isinstance(item, tuple):
                    self.add_sublayer(item[0], item[1])
                else:
                    self.add_sublayer(str(i), item)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return self.__class__(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(idx if idx >= 0 else
                                    len(self) + idx)]

    def __setitem__(self, idx, layer):
        self.add_sublayer(str(idx), layer)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)
        return self
