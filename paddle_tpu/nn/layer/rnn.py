"""RNN layers.

Reference analog: python/paddle/nn/layer/rnn.py (RNNCellBase/LSTMCell/
GRUCell/RNN/BiRNN/LSTM/GRU/SimpleRNN over cudnn rnn kernels). TPU-native:
cells are pure functions stepped by lax.scan (compiler-friendly sequential
control flow — no dynamic python loops under jit).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .layers import Layer, LayerList
from .. import functional as F
from .. import initializer as I
from ...core.tensor import Tensor, apply_op
from ...tensor import manipulation as M

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        state_shape = shape or self.state_shape
        from ...tensor.creation import full

        def build(s):
            return full([batch] + list(s), init_value,
                        dtype or "float32")
        if isinstance(state_shape, (list, tuple)) and state_shape and \
                isinstance(state_shape[0], (list, tuple)):
            return tuple(build(s) for s in state_shape)
        return build(state_shape)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else \
            (lambda v: jnp.maximum(v, 0))
        args = [inputs, states, self.weight_ih, self.weight_hh]
        has_b = self.bias_ih is not None
        if has_b:
            args += [self.bias_ih, self.bias_hh]

        def _f(x, h, wih, whh, *biases):
            z = x @ wih.T + h @ whh.T
            if biases:
                z = z + biases[0] + biases[1]
            return act(z)
        h = apply_op(_f, *args, op_name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        args = [inputs, h, c, self.weight_ih, self.weight_hh]
        has_b = self.bias_ih is not None
        if has_b:
            args += [self.bias_ih, self.bias_hh]
        hs = self.hidden_size

        def _f(x, h_, c_, wih, whh, *biases):
            z = x @ wih.T + h_ @ whh.T
            if biases:
                z = z + biases[0] + biases[1]
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i = lax.logistic(i)
            f = lax.logistic(f)
            g = jnp.tanh(g)
            o = lax.logistic(o)
            new_c = f * c_ + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        new_h, new_c = apply_op(_f, *args, op_name="lstm_cell")
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / np.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=u)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        args = [inputs, states, self.weight_ih, self.weight_hh]
        has_b = self.bias_ih is not None
        if has_b:
            args += [self.bias_ih, self.bias_hh]

        def _f(x, h, wih, whh, *biases):
            zx = x @ wih.T
            zh = h @ whh.T
            if biases:
                zx = zx + biases[0]
                zh = zh + biases[1]
            xr, xz, xc = jnp.split(zx, 3, axis=-1)
            hr, hz, hc = jnp.split(zh, 3, axis=-1)
            r = lax.logistic(xr + hr)
            z = lax.logistic(xz + hz)
            c = jnp.tanh(xc + r * hc)
            return (1 - z) * c + z * h
        new_h = apply_op(_f, *args, op_name="gru_cell")
        return new_h, new_h


class RNN(Layer):
    """Runs a cell over time via an unrolled python loop at the Tensor level
    (tape-friendly); inside jit the loop unrolls into XLA's graph (static
    seq len). For long sequences use the functional scan path."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        states = initial_states
        outputs = []
        idxs = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for i in idxs:
            x_t = M.squeeze(M.slice(inputs, [time_axis], [i], [i + 1]),
                            axis=time_axis)
            out, states = self.cell(x_t, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        out = M.stack(outputs, axis=time_axis)
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        out = M.concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        self.num_directions = bidirect

        def make_cell(in_sz):
            kw = dict(weight_ih_attr=weight_ih_attr,
                      weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if mode == "LSTM":
                return LSTMCell(in_sz, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(in_sz, hidden_size, **kw)
            return SimpleRNNCell(in_sz, hidden_size, **kw)

        self.rnns = LayerList()
        for layer_i in range(num_layers):
            in_sz = input_size if layer_i == 0 \
                else hidden_size * bidirect
            if bidirect == 2:
                self.rnns.append(BiRNN(make_cell(in_sz), make_cell(in_sz),
                                       time_major))
            else:
                self.rnns.append(RNN(make_cell(in_sz),
                                     direction == "backward", time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        final_states = []
        for i, rnn in enumerate(self.rnns):
            init = None
            if initial_states is not None:
                init = self._slice_states(initial_states, i)
            out, st = rnn(out, init)
            final_states.append(st)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        return out, self._stack_states(final_states)

    def _slice_states(self, initial_states, layer_i):
        nd = self.num_directions

        def pick(t, j):
            return M.squeeze(M.slice(t, [0], [j], [j + 1]), axis=0)
        if self.mode == "LSTM":
            h, c = initial_states
            if nd == 2:
                return ((pick(h, 2 * layer_i), pick(c, 2 * layer_i)),
                        (pick(h, 2 * layer_i + 1), pick(c, 2 * layer_i + 1)))
            return (pick(h, layer_i), pick(c, layer_i))
        h = initial_states
        if nd == 2:
            return (pick(h, 2 * layer_i), pick(h, 2 * layer_i + 1))
        return pick(h, layer_i)

    def _stack_states(self, states):
        nd = self.num_directions
        if self.mode == "LSTM":
            hs, cs = [], []
            for st in states:
                if nd == 2:
                    (h1, c1), (h2, c2) = st
                    hs += [h1, h2]
                    cs += [c1, c2]
                else:
                    h, c = st
                    hs.append(h)
                    cs.append(c)
            return M.stack(hs, axis=0), M.stack(cs, axis=0)
        hs = []
        for st in states:
            if nd == 2:
                hs += [st[0], st[1]]
            else:
                hs.append(st)
        return M.stack(hs, axis=0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
