"""Image IO (reference: python/paddle/vision/image.py + decode_jpeg op,
which decodes on-GPU via nvjpeg). Host-side decode here (PIL), producing
the same [C, H, W] uint8 tensor contract."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["read_file", "decode_jpeg", "image_load"]


def read_file(filename, name=None) -> Tensor:
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None) -> Tensor:
    """x: 1-D uint8 tensor of encoded bytes -> [C, H, W] uint8."""
    import io as _io

    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decode_jpeg needs Pillow on the host") from e
    raw = bytes(np.asarray(x._array if isinstance(x, Tensor) else x,
                           np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode != "unchanged":
        img = img.convert(mode.upper() if mode != "gray" else "L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def image_load(path, backend=None):
    from PIL import Image
    return Image.open(path)
