"""Vision datasets (reference: python/paddle/vision/datasets/).

Network download is unavailable (zero-egress); MNIST and friends load from
local files when present, and every dataset supports a synthetic mode
(`backend='synthetic'`) so tests and benchmarks run hermetically — playing
the role of the reference's fake-data CI paths.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "SyntheticImages", "DatasetFolder", "ImageFolder", "Flowers",
           "VOC2012"]


class SyntheticImages(Dataset):
    """Deterministic random images + labels; hermetic stand-in."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28),
                 num_classes=10, seed=0, transform=None):
        rng = np.random.RandomState(seed)
        self.images = rng.randn(num_samples, *image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes,
                                  (num_samples, 1)).astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if backend == "synthetic" or (image_path is None
                                      and not self._find_local()):
            syn = SyntheticImages(2048 if mode == "train" else 512,
                                  (1, 28, 28), 10,
                                  seed=0 if mode == "train" else 1)
            self.images = syn.images
            self.labels = syn.labels
            return
        image_path = image_path or self._local_file(
            "train-images-idx3-ubyte.gz" if mode == "train"
            else "t10k-images-idx3-ubyte.gz")
        label_path = label_path or self._local_file(
            "train-labels-idx1-ubyte.gz" if mode == "train"
            else "t10k-labels-idx1-ubyte.gz")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _cache_dir():
        return os.path.expanduser("~/.cache/paddle_tpu/datasets/mnist")

    def _find_local(self):
        f = os.path.join(self._cache_dir(), "train-images-idx3-ubyte.gz")
        return os.path.exists(f)

    def _local_file(self, name):
        return os.path.join(self._cache_dir(), name)

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        # CHW float in [0,1] — ready for Conv2D without a transform
        return (data.reshape(n, 1, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64).reshape(-1, 1)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    @staticmethod
    def _cache_dir():
        return os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/fashion-mnist")


class _CifarBase(Dataset):
    n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        syn = SyntheticImages(2048 if mode == "train" else 512,
                              (3, 32, 32), self.n_classes,
                              seed=0 if mode == "train" else 1)
        self.images = syn.images
        self.labels = syn.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    n_classes = 10


class Cifar100(_CifarBase):
    n_classes = 100


# ---------------------------------------------------------------------------
# folder datasets (reference: vision/datasets/folder.py)
# ---------------------------------------------------------------------------

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _default_loader(path):
    """numpy-first loader normalized to the repo's [C, H, W] float
    contract: .npy arrays load as stored (assumed CHW); image files
    decode via vision.io (PIL) as HWC and are transposed."""
    if path.endswith(".npy"):
        return np.load(path)
    from .io import image_load
    img = image_load(path)
    arr = np.asarray(img._array if hasattr(img, "_array") else img)
    if arr.ndim == 2:
        arr = arr[None]          # grayscale -> (1, H, W)
    elif arr.ndim == 3:
        arr = arr.transpose(2, 0, 1)  # HWC -> CHW
    return arr.astype(np.float32) / 255.0 if arr.dtype == np.uint8 else arr


def _walk_files(root, exts, is_valid_file):
    """Deterministic recursive walk yielding files passing the filter
    (shared by DatasetFolder/ImageFolder). The walk must stay LAZY so
    the dirs[:] mutation actually prunes hidden directories —
    sorted(os.walk(...)) would exhaust the generator before pruning."""
    for base, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if not d.startswith("."))
        for fname in sorted(files):
            path = os.path.join(base, fname)
            ok = is_valid_file(path) if is_valid_file else \
                fname.lower().endswith(exts)
            if ok:
                yield path


class DatasetFolder(Dataset):
    """Generic <root>/<class_x>/<sample> tree (reference:
    folder.py DatasetFolder — classes from subdirectory names, samples
    gathered per class, loaded lazily)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"DatasetFolder: no class folders in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _walk_files(os.path.join(root, c), exts,
                                    is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"DatasetFolder: no files with extensions {exts} under "
                f"{root}")
        self.targets = [t for _, t in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """Flat (unlabeled) image folder — returns [sample] like the
    reference (folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or IMG_EXTENSIONS))
        self.samples = list(_walk_files(root, exts, is_valid_file))
        if not self.samples:
            raise RuntimeError(f"ImageFolder: no images under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference: vision/datasets/flowers.py). Loads from
    the local cache (~/.cache/paddle_tpu/datasets/flowers: the
    reference's 102flowers.tgz + labels/setid .mat files, pre-extracted
    to images.npy/labels.npy by utils.download tooling) or falls back to
    a deterministic synthetic set in this air-gapped environment."""

    n_classes = 102

    def __init__(self, mode="train", transform=None, download=True,
                 backend=None):
        self.transform = transform
        cache = os.path.expanduser("~/.cache/paddle_tpu/datasets/flowers")
        img_f = os.path.join(cache, f"{mode}_images.npy")
        lab_f = os.path.join(cache, f"{mode}_labels.npy")
        if backend != "synthetic" and os.path.exists(img_f) \
                and os.path.exists(lab_f):  # partial cache -> synthetic
            self.images = np.load(img_f)
            self.labels = np.load(lab_f)
        else:
            syn = SyntheticImages(512 if mode == "train" else 128,
                                  (3, 96, 96), self.n_classes,
                                  seed=7 if mode == "train" else 8)
            self.images, self.labels = syn.images, syn.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference:
    vision/datasets/voc2012.py — returns (image, label_mask)). Local
    cache or deterministic synthetic masks."""

    def __init__(self, mode="train", transform=None, download=True,
                 backend=None):
        self.transform = transform
        cache = os.path.expanduser("~/.cache/paddle_tpu/datasets/voc2012")
        img_f = os.path.join(cache, f"{mode}_images.npy")
        lab_f = os.path.join(cache, f"{mode}_masks.npy")
        if backend != "synthetic" and os.path.exists(img_f) \
                and os.path.exists(lab_f):  # partial cache -> synthetic
            self.images = np.load(img_f)
            self.masks = np.load(lab_f)
        else:
            n = 256 if mode == "train" else 64
            rng = np.random.default_rng(3 if mode == "train" else 4)
            self.images = rng.random((n, 3, 64, 64)).astype(np.float32)
            # blocky class masks: 21 classes incl. background
            small = rng.integers(0, 21, (n, 8, 8))
            self.masks = np.repeat(np.repeat(small, 8, 1), 8, 2) \
                .astype(np.int64)

    def __getitem__(self, idx):
        img, mask = self.images[idx], self.masks[idx]
        if self.transform:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.images)
