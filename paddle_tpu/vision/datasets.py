"""Vision datasets (reference: python/paddle/vision/datasets/).

Network download is unavailable (zero-egress); MNIST and friends load from
local files when present, and every dataset supports a synthetic mode
(`backend='synthetic'`) so tests and benchmarks run hermetically — playing
the role of the reference's fake-data CI paths.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "SyntheticImages"]


class SyntheticImages(Dataset):
    """Deterministic random images + labels; hermetic stand-in."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28),
                 num_classes=10, seed=0, transform=None):
        rng = np.random.RandomState(seed)
        self.images = rng.randn(num_samples, *image_shape).astype(np.float32)
        self.labels = rng.randint(0, num_classes,
                                  (num_samples, 1)).astype(np.int64)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if backend == "synthetic" or (image_path is None
                                      and not self._find_local()):
            syn = SyntheticImages(2048 if mode == "train" else 512,
                                  (1, 28, 28), 10,
                                  seed=0 if mode == "train" else 1)
            self.images = syn.images
            self.labels = syn.labels
            return
        image_path = image_path or self._local_file(
            "train-images-idx3-ubyte.gz" if mode == "train"
            else "t10k-images-idx3-ubyte.gz")
        label_path = label_path or self._local_file(
            "train-labels-idx1-ubyte.gz" if mode == "train"
            else "t10k-labels-idx1-ubyte.gz")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _cache_dir():
        return os.path.expanduser("~/.cache/paddle_tpu/datasets/mnist")

    def _find_local(self):
        f = os.path.join(self._cache_dir(), "train-images-idx3-ubyte.gz")
        return os.path.exists(f)

    def _local_file(self, name):
        return os.path.join(self._cache_dir(), name)

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        # CHW float in [0,1] — ready for Conv2D without a transform
        return (data.reshape(n, 1, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64).reshape(-1, 1)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    @staticmethod
    def _cache_dir():
        return os.path.expanduser(
            "~/.cache/paddle_tpu/datasets/fashion-mnist")


class _CifarBase(Dataset):
    n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        syn = SyntheticImages(2048 if mode == "train" else 512,
                              (3, 32, 32), self.n_classes,
                              seed=0 if mode == "train" else 1)
        self.images = syn.images
        self.labels = syn.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    n_classes = 10


class Cifar100(_CifarBase):
    n_classes = 100
