"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy-based host-side preprocessing (CHW float output convention)."""
from __future__ import annotations

import numbers

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "to_tensor", "normalize", "resize", "hflip",
           "vflip", "center_crop", "crop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._array)
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _as_np(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    from ..core.tensor import to_tensor as tt
    return tt(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, pic):
        return to_tensor(pic, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _as_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    if isinstance(img, Tensor):
        from ..core.tensor import to_tensor as tt
        return tt(out)
    return out


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = _as_np(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    import jax
    import jax.numpy as jnp
    out_shape = (size[0], size[1]) + arr.shape[2:]
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[interpolation]
    out = jax.image.resize(jnp.asarray(arr.astype(np.float32)), out_shape,
                           method=method)
    return np.asarray(out).astype(arr.dtype)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    arr = _as_np(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    arr = _as_np(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = (h - th) // 2
    left = (w - tw) // 2
    return crop(arr, top, left, th, tw)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding

    def __call__(self, img):
        arr = _as_np(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        top = np.random.randint(0, h - th + 1)
        left = np.random.randint(0, w - tw + 1)
        return crop(arr, top, left, th, tw)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _as_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _as_np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = _as_np(img)
        p = self.padding
        if isinstance(p, int):
            cfg = [(p, p), (p, p)]
        elif len(p) == 2:
            cfg = [(p[1], p[1]), (p[0], p[0])]
        else:
            cfg = [(p[1], p[3]), (p[0], p[2])]
        cfg += [(0, 0)] * (arr.ndim - 2)
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect", "symmetric": "symmetric"}[self.mode]
        if mode == "constant":
            return np.pad(arr, cfg, mode=mode, constant_values=self.fill)
        return np.pad(arr, cfg, mode=mode)


# ---------------------------------------------------------------------------
# reference parity: photometric + geometric transform family
# (python/paddle/vision/transforms/transforms.py + functional.py)
# ---------------------------------------------------------------------------

class BaseTransform:
    """reference: transforms.py BaseTransform — the overridable-apply
    protocol (keys routing collapses to the single-image case here;
    subclasses implement _apply_image)."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, img):
        return self._apply_image(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    """functional.pad: HWC padding with constant/edge/reflect modes."""
    arr = _as_np(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(arr, spec, mode=mode, **kw)


def adjust_brightness(img, brightness_factor):
    arr = _as_np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    return np.clip(arr * brightness_factor, 0, hi).astype(_as_np(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = _as_np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    # blend with the mean of the grayscale image (pillow semantics)
    if arr.ndim == 3 and arr.shape[-1] == 3:
        gray = arr @ np.array([0.299, 0.587, 0.114], np.float32)
    else:
        gray = arr
    mean = gray.mean()
    out = mean + contrast_factor * (arr - mean)
    return np.clip(out, 0, hi).astype(_as_np(img).dtype)


def adjust_saturation(img, saturation_factor):
    arr = _as_np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    gray = (arr @ np.array([0.299, 0.587, 0.114], np.float32))[..., None]
    out = gray + saturation_factor * (arr - gray)
    return np.clip(out, 0, hi).astype(_as_np(img).dtype)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) through HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError(f"hue_factor {hue_factor} not in [-0.5, 0.5]")
    arr = _as_np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    x = arr / hi
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = x.max(-1)
    mn = x.min(-1)
    d = mx - mn
    h = np.zeros_like(mx)
    m = d > 1e-12
    rm = m & (mx == r)
    gm = m & (mx == g) & ~rm
    bm = m & ~rm & ~gm
    h[rm] = ((g - b)[rm] / d[rm]) % 6
    h[gm] = (b - r)[gm] / d[gm] + 2
    h[bm] = (r - g)[bm] / d[bm] + 4
    h = h / 6.0
    s = np.where(mx > 1e-12, d / np.maximum(mx, 1e-12), 0.0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], -1) * hi
    return np.clip(out, 0, hi).astype(_as_np(img).dtype)


def to_grayscale(img, num_output_channels=1):
    arr = _as_np(img).astype(np.float32)
    gray = arr @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return out.astype(_as_np(img).dtype)


def _affine_sample(arr, matrix, fill=0):
    """Inverse-warp HWC by the 2x3 INVERSE affine matrix (output->input
    coords about the image center), nearest sampling."""
    h, w = arr.shape[:2]
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    xs = xx - cx
    ys = yy - cy
    sx = matrix[0, 0] * xs + matrix[0, 1] * ys + matrix[0, 2] + cx
    sy = matrix[1, 0] * xs + matrix[1, 1] * ys + matrix[1, 2] + cy
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    sxi = np.clip(sxi, 0, w - 1)
    syi = np.clip(syi, 0, h - 1)
    out = arr[syi, sxi]
    if arr.ndim == 3:
        out = np.where(valid[..., None], out, fill)
    else:
        out = np.where(valid, out, fill)
    return out.astype(arr.dtype)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """functional.affine — rotate/translate/scale/shear about the
    center (matrix composed the reference way, then inverted for the
    backward warp)."""
    arr = _as_np(img)
    # positive angle = counter-clockwise in IMAGE coordinates (pillow/
    # reference convention); array y points down, so negate
    a = -np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in (
        shear if isinstance(shear, (list, tuple)) else (shear, 0.0)))
    # forward matrix: R(angle) * Shear * Scale
    m = np.array([
        [np.cos(a + sy) / max(np.cos(sy), 1e-9), 
         np.cos(a + sy) * np.tan(sx) / max(np.cos(sy), 1e-9)
         - np.sin(a), 0.0],
        [np.sin(a + sy) / max(np.cos(sy), 1e-9),
         np.sin(a + sy) * np.tan(sx) / max(np.cos(sy), 1e-9)
         + np.cos(a), 0.0]], np.float64) * scale
    fwd = np.vstack([m, [0, 0, 1]])
    fwd[0, 2] = translate[0]
    fwd[1, 2] = translate[1]
    inv = np.linalg.inv(fwd)
    return _affine_sample(arr, inv[:2], fill=fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """functional.rotate: counter-clockwise rotation; ``expand`` grows
    the canvas to hold the whole rotated image; ``center`` moves the
    pivot (image-coordinate (x, y), default the center)."""
    arr = _as_np(img)
    h, w = arr.shape[:2]
    if expand:
        a = np.deg2rad(angle)
        new_w = int(np.ceil(abs(w * np.cos(a)) + abs(h * np.sin(a))))
        new_h = int(np.ceil(abs(w * np.sin(a)) + abs(h * np.cos(a))))
        # embed into the bigger canvas first, then rotate about ITS
        # center — every source pixel stays inside
        pt = (new_h - h) // 2
        pl = (new_w - w) // 2
        spec = [(pt, new_h - h - pt), (pl, new_w - w - pl)] +             [(0, 0)] * (arr.ndim - 2)
        arr = np.pad(arr, spec, constant_values=fill)
        return affine(arr, angle, (0, 0), 1.0, (0.0, 0.0), fill=fill)
    if center is not None:
        # conjugate by the pivot shift: T(c) R T(-c) about the default
        # center equals rotation about `center`
        cx, cy = center
        dx = cx - (w - 1) / 2.0
        dy = cy - (h - 1) / 2.0
        a = -np.deg2rad(angle)
        # translation the rotation-about-center formulation needs
        tx = dx - (np.cos(a) * dx - np.sin(a) * dy)
        ty = dy - (np.sin(a) * dx + np.cos(a) * dy)
        return affine(arr, angle, (tx, ty), 1.0, (0.0, 0.0), fill=fill)
    return affine(img, angle, (0, 0), 1.0, (0.0, 0.0), fill=fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """functional.perspective — warp by the homography mapping
    startpoints -> endpoints (solved least-squares, inverse-sampled)."""
    arr = _as_np(img)
    A = []
    bv = []
    for (x, y), (u, v) in zip(endpoints, startpoints):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bv += [u, v]
    coef = np.linalg.lstsq(np.asarray(A, np.float64),
                           np.asarray(bv, np.float64), rcond=None)[0]
    Hm = np.append(coef, 1.0).reshape(3, 3)
    h, w = arr.shape[:2]
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = Hm[2, 0] * xx + Hm[2, 1] * yy + Hm[2, 2]
    sx = (Hm[0, 0] * xx + Hm[0, 1] * yy + Hm[0, 2]) / den
    sy = (Hm[1, 0] * xx + Hm[1, 1] * yy + Hm[1, 2]) / den
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    sxi = np.clip(sxi, 0, w - 1)
    syi = np.clip(syi, 0, h - 1)
    out = arr[syi, sxi]
    mask = valid[..., None] if arr.ndim == 3 else valid
    return np.where(mask, out, fill).astype(arr.dtype)


def erase(img, i, j, h, w, v, inplace=False):
    """functional.erase — fill the [i:i+h, j:j+w] region with v.
    Accepts HWC arrays or CHW Tensors (the post-ToTensor case)."""
    if isinstance(img, Tensor):
        arr = np.asarray(img._array).copy()
        arr[..., i:i + h, j:j + w] = v
        from ..core.tensor import to_tensor as tt
        return tt(arr)
    arr = _as_np(img) if inplace else _as_np(img).copy()
    arr[i:i + h, j:j + w] = v
    return arr


class RandomResizedCrop(BaseTransform):
    """reference: RandomResizedCrop — random area/aspect crop resized
    to `size` (the ImageNet training crop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _as_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            log_r = np.log(self.ratio)
            ar = np.exp(np.random.uniform(*log_r))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = arr[top:top + ch, left:left + cw]
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size,
                      self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """reference: ColorJitter — apply the four photometric jitters in
    random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill

    def _apply_image(self, img):
        arr = _as_np(img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        sh = (np.random.uniform(-self.shear, self.shear)
              if isinstance(self.shear, numbers.Number) and self.shear
              else 0.0)
        return affine(img, angle, (tx, ty), sc, (sh, 0.0),
                      fill=self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _as_np(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        jitter = lambda lo, hi: np.random.randint(lo, hi + 1)  # noqa: E731
        end = [(jitter(0, dx), jitter(0, dy)),
               (w - 1 - jitter(0, dx), jitter(0, dy)),
               (w - 1 - jitter(0, dx), h - 1 - jitter(0, dy)),
               (jitter(0, dx), h - 1 - jitter(0, dy))]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    """reference: RandomErasing — cutout over a random region; operates
    post-ToTensor (CHW Tensor) or on HWC arrays."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img._array) if isinstance(img, Tensor) \
            else _as_np(img)
        if isinstance(img, Tensor):
            h, w = arr.shape[-2:]
        else:
            h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(*np.log(self.ratio)))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return erase(img, i, j, eh, ew, self.value)
        return img


__all__ += ["BaseTransform", "RandomResizedCrop", "BrightnessTransform",
            "SaturationTransform", "ContrastTransform", "HueTransform",
            "ColorJitter", "RandomAffine", "RandomRotation",
            "RandomPerspective", "Grayscale", "RandomErasing", "pad",
            "affine", "rotate", "perspective", "to_grayscale",
            "adjust_brightness", "adjust_contrast", "adjust_hue",
            "adjust_saturation", "erase"]
