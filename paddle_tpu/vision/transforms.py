"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy-based host-side preprocessing (CHW float output convention)."""
from __future__ import annotations

import numbers

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "to_tensor", "normalize", "resize", "hflip",
           "vflip", "center_crop", "crop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def _as_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._array)
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    arr = _as_np(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    from ..core.tensor import to_tensor as tt
    return tt(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, pic):
        return to_tensor(pic, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _as_np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    if isinstance(img, Tensor):
        from ..core.tensor import to_tensor as tt
        return tt(out)
    return out


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    arr = _as_np(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    import jax
    import jax.numpy as jnp
    out_shape = (size[0], size[1]) + arr.shape[2:]
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[interpolation]
    out = jax.image.resize(jnp.asarray(arr.astype(np.float32)), out_shape,
                           method=method)
    return np.asarray(out).astype(arr.dtype)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


def crop(img, top, left, height, width):
    arr = _as_np(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    arr = _as_np(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = (h - th) // 2
    left = (w - tw) // 2
    return crop(arr, top, left, th, tw)


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        if isinstance(size, int):
            size = (size, size)
        self.size = size
        self.padding = padding

    def __call__(self, img):
        arr = _as_np(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        top = np.random.randint(0, h - th + 1)
        left = np.random.randint(0, w - tw + 1)
        return crop(arr, top, left, th, tw)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return _as_np(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return _as_np(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = _as_np(img)
        p = self.padding
        if isinstance(p, int):
            cfg = [(p, p), (p, p)]
        elif len(p) == 2:
            cfg = [(p[1], p[1]), (p[0], p[0])]
        else:
            cfg = [(p[1], p[3]), (p[0], p[2])]
        cfg += [(0, 0)] * (arr.ndim - 2)
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect", "symmetric": "symmetric"}[self.mode]
        if mode == "constant":
            return np.pad(arr, cfg, mode=mode, constant_values=self.fill)
        return np.pad(arr, cfg, mode=mode)
