"""Vision ops (reference: python/paddle/vision/ops.py + operators/detection).

Round-1 subset: nms, box conversion, roi_align (vectorized bilinear), yolo
boxes deferred.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = ["nms", "box_iou", "roi_align", "deform_conv2d"]


def box_iou(boxes1, boxes2):
    b1 = np.asarray(_ensure_tensor(boxes1)._array)
    b2 = np.asarray(_ensure_tensor(boxes2)._array)
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = np.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = np.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return Tensor(jnp.asarray(inter / np.maximum(union, 1e-10)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS — host-side (dynamic output), like the reference op."""
    b = np.asarray(_ensure_tensor(boxes)._array)
    if scores is None:
        s = np.ones(len(b), np.float32)
    else:
        s = np.asarray(_ensure_tensor(scores)._array)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        w = np.clip(xx2 - xx1, 0, None)
        h = np.clip(yy2 - yy1, 0, None)
        inter = w * h
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = False
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = _ensure_tensor(x)
    boxes = _ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(_ensure_tensor(boxes_num)._array)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def _f(feat, bxs):
        n_roi = bxs.shape[0]
        c = feat.shape[1]
        h, w = feat.shape[2], feat.shape[3]
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (rh / oh)[:, None]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (rw / ow)[:, None]

        def bilinear(fmap, yy, xx):
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = yy - y0
            wx = xx - x0
            v00 = fmap[:, y0][:, :, x0]
            # vectorized gather per roi handled below instead
            return None

        outs = []
        for r in range(n_roi):
            fmap = feat[batch_idx[r]]  # [C,H,W]
            yy = ys[r][:, None]  # [oh,1]
            xx = xs[r][None, :]  # [1,ow]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            g = lambda yi, xi: fmap[:, yi.squeeze(-1) if yi.ndim > 2 else yi,
                                    :][:, :, xi.squeeze(0) if xi.ndim > 2
                                       else xi]
            v00 = fmap[:, y0[:, 0]][:, :, x0[0, :]]
            v01 = fmap[:, y0[:, 0]][:, :, x1_[0, :]]
            v10 = fmap[:, y1_[:, 0]][:, :, x0[0, :]]
            v11 = fmap[:, y1_[:, 0]][:, :, x1_[0, :]]
            val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                   + v10 * wy * (1 - wx) + v11 * wy * wx)
            outs.append(val)
        return jnp.stack(outs)
    return apply_op(_f, x, boxes, op_name="roi_align")


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d: planned (needs a gather-based Pallas kernel)")


for _n in ["nms", "box_iou", "roi_align"]:
    register(_n, globals()[_n])
