"""Vision ops (reference: python/paddle/vision/ops.py + operators/detection):
nms/matrix_nms, box_iou/box_coder, prior_box, yolo_box, roi_align/roi_pool/
psroi_pool, distribute_fpn_proposals, generate_proposals, deform_conv2d
(+DeformConv2D layer). Detection ops with dynamic output sizes run host-side
(like the reference CPU kernels); dense/differentiable ops are jnp.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = ["nms", "nms_padded", "multiclass_nms", "box_iou", "roi_align",
           "deform_conv2d", "box_coder", "prior_box", "yolo_box",
           "yolo_loss", "roi_pool", "psroi_pool", "matrix_nms",
           "distribute_fpn_proposals", "generate_proposals",
           "DeformConv2D", "RoIAlign", "RoIPool", "PSRoIPool"]


from ..ops.registry import host_only_guard as _host_only  # noqa: E402


def box_iou(boxes1, boxes2):
    _host_only("box_iou", boxes1, boxes2)
    b1 = np.asarray(_ensure_tensor(boxes1)._array)
    b2 = np.asarray(_ensure_tensor(boxes2)._array)
    area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
    area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
    lt = np.maximum(b1[:, None, :2], b2[None, :, :2])
    rb = np.minimum(b1[:, None, 2:], b2[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return Tensor(jnp.asarray(inter / np.maximum(union, 1e-10)))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS — host-side (dynamic output), like the reference op."""
    _host_only("nms", boxes, scores, alternative="nms_padded")
    b = np.asarray(_ensure_tensor(boxes)._array)
    if scores is None:
        s = np.ones(len(b), np.float32)
    else:
        s = np.asarray(_ensure_tensor(scores)._array)
    keep = _greedy_nms_np(b, s, iou_threshold)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def nms_padded(boxes, scores, iou_threshold=0.3, max_out=None):
    """Greedy NMS with a FIXED-SIZE output — the jit/TPU-compilable form.

    Reference analog: the detection suite's nms with a static top-k
    contract (operators/detection/nms_op + multiclass_nms keep_top_k).
    Returns (keep_idx int32[max_out], valid bool[max_out]): the first
    count(valid) entries are the kept box indices in score order;
    padding entries have valid False. Same greedy-suppression order as
    `nms`, but expressed as an argmax-select-suppress scan over a
    precomputed IoU matrix — static shapes, compiles under jit and
    shards like any dense op.
    """
    import jax
    from jax import lax

    b_arr = getattr(boxes, "_array", boxes)
    s_arr = getattr(scores, "_array", scores)
    n = b_arr.shape[0]
    m = n if max_out is None else int(max_out)

    def _impl(bx, sc):
        bx = bx.astype(jnp.float32)
        area = (bx[:, 2] - bx[:, 0]) * (bx[:, 3] - bx[:, 1])
        lt = jnp.maximum(bx[:, None, :2], bx[None, :, :2])
        rb = jnp.minimum(bx[:, None, 2:], bx[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)

        neg = jnp.float32(-jnp.inf)

        def step(work, _):
            i = jnp.argmax(work)
            valid = work[i] > neg
            sup = jnp.where(valid & (iou[i] > iou_threshold), neg, work)
            work = jnp.where(valid, sup.at[i].set(neg), work)
            return work, (i.astype(jnp.int32), valid)

        _, (idx, valid) = lax.scan(step, sc.astype(jnp.float32),
                                   None, length=m)
        return idx, valid

    idx, valid = _impl(jnp.asarray(b_arr), jnp.asarray(s_arr))
    if isinstance(boxes, Tensor) or isinstance(scores, Tensor):
        return Tensor(idx), Tensor(valid)
    return idx, valid


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    x = _ensure_tensor(x)
    boxes = _ensure_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    _host_only("roi_align (boxes_num)", boxes_num)
    bn = np.asarray(_ensure_tensor(boxes_num)._array)
    batch_idx = np.repeat(np.arange(len(bn)), bn)

    def _f(feat, bxs):
        n_roi = bxs.shape[0]
        c = feat.shape[1]
        h, w = feat.shape[2], feat.shape[3]
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        ys = y1[:, None] + (jnp.arange(oh) + 0.5)[None, :] * (rh / oh)[:, None]
        xs = x1[:, None] + (jnp.arange(ow) + 0.5)[None, :] * (rw / ow)[:, None]

        outs = []
        for r in range(n_roi):
            fmap = feat[batch_idx[r]]  # [C,H,W]
            yy = ys[r][:, None]  # [oh,1]
            xx = xs[r][None, :]  # [1,ow]
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy - y0, 0, 1)
            wx = jnp.clip(xx - x0, 0, 1)
            v00 = fmap[:, y0[:, 0]][:, :, x0[0, :]]
            v01 = fmap[:, y0[:, 0]][:, :, x1_[0, :]]
            v10 = fmap[:, y1_[:, 0]][:, :, x0[0, :]]
            v11 = fmap[:, y1_[:, 0]][:, :, x1_[0, :]]
            val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                   + v10 * wy * (1 - wx) + v11 * wy * wx)
            outs.append(val)
        return jnp.stack(outs)
    return apply_op(_f, x, boxes, op_name="roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference:
    operators/deformable_conv_op + python/paddle/vision/ops.py). Sampling
    positions are the regular conv grid displaced by learned per-position
    offsets; v2 additionally modulates samples by ``mask``. Gather-based
    bilinear sampling in jnp — differentiable through offsets, mask, x,
    and weight.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo] with (dy, dx)
    channel pairs; weight: [Cout, Cin//groups, kh, kw];
    mask: [N, dg*kh*kw, Ho, Wo] or None.
    """
    x = _ensure_tensor(x)
    offset = _ensure_tensor(offset)
    weight = _ensure_tensor(weight)
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    Cout, Cin_g, kh, kw = weight.shape
    args = [x, offset, weight]
    has_mask = mask is not None
    if has_mask:
        args.append(_ensure_tensor(mask))
    if bias is not None:
        args.append(_ensure_tensor(bias))

    def _f(xa, off, w, *rest):
        m = rest[0] if has_mask else None
        b = rest[-1] if bias is not None else None
        N, Cin, H, W = xa.shape
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        dg = deformable_groups
        cpg = Cin // dg    # channels per deform group
        base_i = jnp.arange(Ho) * sh - ph
        base_j = jnp.arange(Wo) * sw - pw
        xf = xa.astype(jnp.float32)
        cols = []  # per (r, s): [N, Cin, Ho, Wo]
        for r in range(kh):
            for s in range(kw):
                kidx = r * kw + s
                per_g = []
                for g_ in range(dg):
                    dy = off[:, 2 * (g_ * kh * kw + kidx)]
                    dx = off[:, 2 * (g_ * kh * kw + kidx) + 1]
                    py = base_i[None, :, None] + r * dh \
                        + dy.astype(jnp.float32)
                    px = base_j[None, None, :] + s * dw \
                        + dx.astype(jnp.float32)
                    y0 = jnp.floor(py)
                    x0 = jnp.floor(px)
                    wy = py - y0
                    wx = px - x0
                    pieces = 0.0
                    for (yy, cy) in ((y0, 1 - wy), (y0 + 1, wy)):
                        for (xx, cx) in ((x0, 1 - wx), (x0 + 1, wx)):
                            inb = ((yy >= 0) & (yy <= H - 1)
                                   & (xx >= 0) & (xx <= W - 1))
                            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                            ch = xf[:, g_ * cpg:(g_ + 1) * cpg]
                            # flat gather: position index varies with BOTH
                            # output coords, so index the H*W plane
                            lin = (yi * W + xi).reshape(N, 1, Ho * Wo)
                            v = jnp.take_along_axis(
                                ch.reshape(N, cpg, H * W),
                                jnp.broadcast_to(lin, (N, cpg, Ho * Wo)),
                                axis=2).reshape(N, cpg, Ho, Wo)
                            coef = (cy * cx
                                    * inb.astype(jnp.float32))[:, None]
                            pieces = pieces + v * coef
                    if m is not None:
                        pieces = pieces * m[:, g_ * kh * kw + kidx,
                                            None].astype(jnp.float32)
                    per_g.append(pieces)
                cols.append(jnp.concatenate(per_g, axis=1))
        col = jnp.stack(cols, axis=2)  # [N, Cin, kh*kw, Ho, Wo]
        col = col.reshape(N, groups, Cin // groups, kh * kw, Ho, Wo)
        wg = w.astype(jnp.float32).reshape(
            groups, Cout // groups, Cin_g, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", col, wg)
        out = out.reshape(N, Cout, Ho, Wo).astype(xa.dtype)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    return apply_op(_f, *args, op_name="deform_conv2d")


for _n in ["nms", "box_iou", "roi_align"]:
    register(_n, globals()[_n])


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (reference:
    operators/detection/box_coder_op). prior_box: [M, 4] (x1,y1,x2,y2);
    prior_box_var: [M, 4] | [4] | None; encode: target [N, 4] -> [N, M, 4];
    decode: target [N, M, 4] -> [N, M, 4]."""
    _host_only("box_coder", prior_box, target_box, prior_box_var)
    pb = np.asarray(_ensure_tensor(prior_box)._array, np.float32)
    tb = np.asarray(_ensure_tensor(target_box)._array, np.float32)
    pbv = None if prior_box_var is None else \
        np.asarray(_ensure_tensor(prior_box_var)._array, np.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        dx = (tcx[:, None] - pcx[None]) / pw[None]
        dy = (tcy[:, None] - pcy[None]) / ph[None]
        dw = np.log(np.abs(tw[:, None] / pw[None]))
        dh = np.log(np.abs(th[:, None] / ph[None]))
        out = np.stack([dx, dy, dw, dh], -1)
        if pbv is not None:
            out = out / (pbv[None] if pbv.ndim == 2 else pbv.reshape(1, 1, 4))
    elif code_type == "decode_center_size":
        if pbv is None:
            var = np.ones((1, 1, 4), np.float32)
        elif pbv.ndim == 1:
            var = pbv.reshape(1, 1, 4)
        else:
            var = pbv[None] if axis == 0 else pbv[:, None]
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = pw[None, :, None], ph[None, :, None], \
                pcx[None, :, None], pcy[None, :, None]
        else:
            pw_, ph_, pcx_, pcy_ = pw[:, None, None], ph[:, None, None], \
                pcx[:, None, None], pcy[:, None, None]
        d = tb * var
        cx = d[..., 0:1] * pw_ + pcx_
        cy = d[..., 1:2] * ph_ + pcy_
        w = np.exp(d[..., 2:3]) * pw_
        h = np.exp(d[..., 3:4]) * ph_
        out = np.concatenate([cx - w / 2, cy - h / 2,
                              cx + w / 2 - norm, cy + h / 2 - norm], -1)
    else:
        raise ValueError(f"unknown code_type {code_type!r}")
    return Tensor(jnp.asarray(out))


def prior_box(input, image, min_sizes, max_sizes=None,  # noqa: A002
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes (reference: operators/detection/
    prior_box_op). Returns (boxes [H, W, P, 4], variances same shape)."""
    feat = _ensure_tensor(input)
    img = _ensure_tensor(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = []
        if min_max_aspect_ratios_order:
            sizes.append((ms, ms))
            if max_sizes:
                mx = max_sizes[ms_i]
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[ms_i]
                sizes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
        boxes.append(sizes)
    per_cell = [wh for group in boxes for wh in group]
    P = len(per_cell)
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    out = np.zeros((fh, fw, P, 4), np.float32)
    for p, (bw, bh) in enumerate(per_cell):
        out[:, :, p, 0] = (cx[None, :] - bw / 2) / iw
        out[:, :, p, 1] = (cy[:, None] - bh / 2) / ih
        out[:, :, p, 2] = (cx[None, :] + bw / 2) / iw
        out[:, :, p, 3] = (cy[:, None] + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None,
             scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output [N, P*(5+C), H, W] into boxes + scores
    (reference: operators/detection/yolo_box_op)."""
    _host_only("yolo_box", x, img_size)
    xa = np.asarray(_ensure_tensor(x)._array, np.float32)
    imgs = np.asarray(_ensure_tensor(img_size)._array)
    N, _, H, W = xa.shape
    P = len(anchors) // 2
    sig0 = lambda v: 1.0 / (1.0 + np.exp(-v))  # noqa: E731
    ioup = None
    if iou_aware:
        # iou-aware head: first P channels are per-anchor IoU logits,
        # the rest is the standard [P, 5+C] block (reference yolo_box_op)
        ioup = sig0(xa[:, :P].reshape(N, P, H, W))
        xa = xa[:, P:]
    xa = xa.reshape(N, P, 5 + class_num, H, W)
    grid_x = np.arange(W).reshape(1, 1, 1, W)
    grid_y = np.arange(H).reshape(1, 1, H, 1)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))  # noqa: E731
    bx = (sig(xa[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_x) / W
    by = (sig(xa[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_y) / H
    aw = np.asarray(anchors[0::2], np.float32).reshape(1, P, 1, 1)
    ah = np.asarray(anchors[1::2], np.float32).reshape(1, P, 1, 1)
    in_w = downsample_ratio * W
    in_h = downsample_ratio * H
    bw = np.exp(xa[:, :, 2]) * aw / in_w
    bh = np.exp(xa[:, :, 3]) * ah / in_h
    conf = sig(xa[:, :, 4])
    if ioup is not None:
        conf = conf ** (1.0 - iou_aware_factor) * ioup ** iou_aware_factor
    cls = sig(xa[:, :, 5:])
    scores = (conf[:, :, None] * cls)
    ih = imgs[:, 0].astype(np.float32).reshape(N, 1, 1, 1)
    iw = imgs[:, 1].astype(np.float32).reshape(N, 1, 1, 1)
    x1 = (bx - bw / 2) * iw
    y1 = (by - bh / 2) * ih
    x2 = (bx + bw / 2) * iw
    y2 = (by + bh / 2) * ih
    if clip_bbox:
        x1 = np.clip(x1, 0, iw - 1)
        y1 = np.clip(y1, 0, ih - 1)
        x2 = np.clip(x2, 0, iw - 1)
        y2 = np.clip(y2, 0, ih - 1)
    boxes = np.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
    scores = np.moveaxis(scores, 2, -1).reshape(N, -1, class_num)
    keep = conf.reshape(N, -1) >= conf_thresh
    boxes = boxes * keep[..., None]
    scores = scores * keep[..., None]
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(scores))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max-pool each ROI into a fixed grid (reference: roi_pool_op)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    _host_only("roi_pool", x, boxes, boxes_num)
    feat = np.asarray(_ensure_tensor(x)._array, np.float32)
    bxs = np.asarray(_ensure_tensor(boxes)._array, np.float32)
    bn = np.asarray(_ensure_tensor(boxes_num)._array)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    C, H, W = feat.shape[1:]
    outs = np.zeros((len(bxs), C, oh, ow), np.float32)
    for r, bx in enumerate(bxs):
        fmap = feat[batch_idx[r]]
        x1 = int(round(bx[0] * spatial_scale))
        y1 = int(round(bx[1] * spatial_scale))
        x2 = int(round(bx[2] * spatial_scale))
        y2 = int(round(bx[3] * spatial_scale))
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        for i in range(oh):
            ys = y1 + int(np.floor(i * rh / oh))
            ye = y1 + int(np.ceil((i + 1) * rh / oh))
            ys, ye = np.clip([ys, ye], 0, H)
            for j in range(ow):
                xs = x1 + int(np.floor(j * rw / ow))
                xe = x1 + int(np.ceil((j + 1) * rw / ow))
                xs, xe = np.clip([xs, xe], 0, W)
                if ye > ys and xe > xs:
                    outs[r, :, i, j] = fmap[:, ys:ye, xs:xe].max((1, 2))
    return Tensor(jnp.asarray(outs))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive ROI average pooling (reference: psroi_pool_op):
    input channels C = out_c * oh * ow; bin (i, j) reads its own channel
    group."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    _host_only("psroi_pool", x, boxes, boxes_num)
    feat = np.asarray(_ensure_tensor(x)._array, np.float32)
    bxs = np.asarray(_ensure_tensor(boxes)._array, np.float32)
    bn = np.asarray(_ensure_tensor(boxes_num)._array)
    batch_idx = np.repeat(np.arange(len(bn)), bn)
    C, H, W = feat.shape[1:]
    if C % (oh * ow):
        raise ValueError(f"channels {C} not divisible by {oh}x{ow}")
    out_c = C // (oh * ow)
    outs = np.zeros((len(bxs), out_c, oh, ow), np.float32)
    for r, bx in enumerate(bxs):
        fmap = feat[batch_idx[r]]
        x1 = bx[0] * spatial_scale
        y1 = bx[1] * spatial_scale
        rh = max(bx[3] * spatial_scale - y1, 0.1)
        rw = max(bx[2] * spatial_scale - x1, 0.1)
        for i in range(oh):
            ys = int(np.floor(y1 + i * rh / oh))
            ye = int(np.ceil(y1 + (i + 1) * rh / oh))
            ys, ye = np.clip([ys, ye], 0, H)
            for j in range(ow):
                xs = int(np.floor(x1 + j * rw / ow))
                xe = int(np.ceil(x1 + (j + 1) * rw / ow))
                xs, xe = np.clip([xs, xe], 0, W)
                if ye > ys and xe > xs:
                    grp = (i * ow + j) * out_c
                    outs[r, :, i, j] = fmap[grp:grp + out_c,
                                            ys:ye, xs:xe].mean((1, 2))
    return Tensor(jnp.asarray(outs))


def _greedy_nms_np(b, s, thr, normalized=True, eta=1.0):
    """Greedy suppression core shared by nms/multiclass_nms.
    normalized=False adds the reference's +1 pixel offset to areas/
    intersections; eta < 1 adaptively tightens the threshold after each
    kept box (the SSD nms_eta contract)."""
    norm = 0.0 if normalized else 1.0
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0] + norm) * (b[:, 3] - b[:, 1] + norm)
    adaptive = thr
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1 + norm, 0, None) * \
            np.clip(yy2 - yy1 + norm, 0, None)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > adaptive
        suppressed[i] = False
        if eta < 1.0 and adaptive > 0.5:
            adaptive *= eta
    return np.asarray(keep, np.int64)


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   return_rois_num=True, rois_num=None, name=None):
    """Per-class greedy NMS + cross-class top-k (reference:
    operators/detection/multiclass_nms_op / multiclass_nms3). Host-side
    like the reference CPU kernel (dynamic output count).

    Batched form: bboxes [N, M, 4], scores [N, C, M]. Dynamic-ROIs form
    (rois_num given): bboxes [M, 4], scores [M, C] with rois_num [N]
    splitting the M rows per image. background_label defaults to 0 like
    the reference (pass -1 to keep every class). Returns (out [K, 6]
    rows of [label, score, x1, y1, x2, y2], optional flat index,
    rois_num [N]).
    """
    _host_only("multiclass_nms", bboxes, scores)
    bb = np.asarray(_ensure_tensor(bboxes)._array, np.float32)
    sc = np.asarray(_ensure_tensor(scores)._array, np.float32)
    if rois_num is not None:
        rn = np.asarray(_ensure_tensor(rois_num)._array).reshape(-1)
        if bb.ndim != 2 or sc.ndim != 2:
            raise ValueError(
                "multiclass_nms with rois_num expects bboxes [M, 4] and "
                f"scores [M, C]; got {bb.shape} / {sc.shape}")
        starts = np.concatenate([[0], np.cumsum(rn)]).astype(int)
        groups = [(bb[starts[i]:starts[i + 1]],
                   sc[starts[i]:starts[i + 1]].T,  # -> [C, m]
                   starts[i]) for i in range(len(rn))]
    else:
        groups = [(bb[n], sc[n], n * bb.shape[1])
                  for n in range(bb.shape[0])]
    outs, idxs, counts = [], [], []
    for boxes_n, scores_n, base in groups:
        C = scores_n.shape[0]
        dets = []  # (label, score, box, flat_index)
        for c in range(C):
            if c == background_label:
                continue
            cand = np.nonzero(scores_n[c] > score_threshold)[0]
            if cand.size == 0:
                continue
            if nms_top_k > -1 and cand.size > nms_top_k:
                cand = cand[np.argsort(-scores_n[c, cand])[:nms_top_k]]
            keep = _greedy_nms_np(boxes_n[cand], scores_n[c, cand],
                                  nms_threshold, normalized=normalized,
                                  eta=nms_eta)
            for j in cand[keep]:
                dets.append((c, scores_n[c, j], boxes_n[j], base + j))
        dets.sort(key=lambda d: -d[1])
        if keep_top_k > -1:  # reference: 0 keeps nothing, -1 unlimited
            dets = dets[:keep_top_k]
        counts.append(len(dets))
        for c, s, box, fi in dets:
            # box is already a host numpy row here — unpack it directly
            # (a .tolist() per detection reads as a per-iteration sync)
            outs.append([float(c), float(s), *box])
            idxs.append(fi)
    out = Tensor(jnp.asarray(np.asarray(outs, np.float32).reshape(-1, 6)))
    nums = Tensor(jnp.asarray(np.asarray(counts, np.int32)))
    index = Tensor(jnp.asarray(np.asarray(idxs, np.int64).reshape(-1, 1)))
    if return_index:
        return (out, index, nums) if return_rois_num else (out, index)
    return (out, nums) if return_rois_num else out


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0,
               normalized=True, return_index=False, return_rois_num=True,
               name=None):
    """Matrix NMS (SOLOv2; reference: operators/detection/matrix_nms_op):
    parallel soft suppression by decayed IoU instead of greedy removal.
    bboxes [N, M, 4], scores [N, C, M]."""
    _host_only("matrix_nms", bboxes, scores)
    bb = np.asarray(_ensure_tensor(bboxes)._array, np.float32)
    sc = np.asarray(_ensure_tensor(scores)._array, np.float32)
    N, C, M = sc.shape
    all_out, all_idx, rois_num = [], [], []
    for n in range(N):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            mask = sc[n, c] > score_threshold
            idxs = np.nonzero(mask)[0]
            if len(idxs) == 0:
                continue
            s = sc[n, c, idxs]
            order = np.argsort(-s)
            if nms_top_k > 0:
                order = order[:nms_top_k]
            idxs, s = idxs[order], s[order]
            b = bb[n, idxs]
            norm = 0.0 if normalized else 1.0
            area = (b[:, 2] - b[:, 0] + norm) * (b[:, 3] - b[:, 1] + norm)
            lt = np.maximum(b[:, None, :2], b[None, :, :2])
            rb = np.minimum(b[:, None, 2:], b[None, :, 2:])
            wh = np.clip(rb - lt + norm, 0, None)
            inter = wh[..., 0] * wh[..., 1]
            iou = inter / np.maximum(area[:, None] + area[None] - inter,
                                     1e-10)
            iou = np.triu(iou, 1)
            # compensate IoU: for suppressor i, its own max overlap with
            # any higher-ranked box (reference matrix_nms_op kernel);
            # broadcast per ROW (the suppressor), not per column
            iou_cmax = iou.max(0)
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None] ** 2 - iou ** 2)
                               / gaussian_sigma)
                decay = decay.min(0)
            else:
                decay = ((1 - iou)
                         / np.maximum(1 - iou_cmax[:, None], 1e-10)).min(0)
            ds = s * decay
            keep = ds > post_threshold
            for k in np.nonzero(keep)[0]:
                dets.append((c, ds[k], b[k], idxs[k]))
        dets.sort(key=lambda d: -d[1])
        if keep_top_k > -1:  # reference: 0 keeps nothing, -1 unlimited
            dets = dets[:keep_top_k]
        out = np.asarray([[d[0], d[1], *d[2]] for d in dets],
                         np.float32).reshape(-1, 6)
        all_out.append(out)
        all_idx.append(np.asarray([d[3] for d in dets], np.int64))
        rois_num.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(all_out, 0)
                             if all_out else np.zeros((0, 6), np.float32)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(
            np.concatenate(all_idx) if all_idx else
            np.zeros((0,), np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(res) if len(res) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign each ROI to an FPN level by its scale (reference:
    operators/detection/distribute_fpn_proposals_op). With ``rois_num``
    (per-image counts for a batched roi list) each level's count output
    is itself per-image."""
    _host_only("distribute_fpn_proposals", fpn_rois)
    rois = np.asarray(_ensure_tensor(fpn_rois)._array, np.float32)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip((rois[:, 2] - rois[:, 0] + off)
                            * (rois[:, 3] - rois[:, 1] + off), 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        rn = np.asarray(_ensure_tensor(rois_num)._array).reshape(-1)
        img_of = np.repeat(np.arange(len(rn)), rn)
        n_imgs = len(rn)
    else:
        img_of = np.zeros(len(rois), np.int64)
        n_imgs = 1
    multi_rois, restore = [], np.zeros(len(rois), np.int64)
    nums = []
    cursor = 0
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        # within a level, keep image order (stable: idx is sorted and
        # rois arrive grouped per image)
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        per_img = np.bincount(img_of[idx], minlength=n_imgs) \
            .astype(np.int32)
        nums.append(Tensor(jnp.asarray(per_img)))
        restore[idx] = np.arange(cursor, cursor + len(idx))
        cursor += len(idx)
    return multi_rois, Tensor(jnp.asarray(restore.reshape(-1, 1))), nums


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation: decode deltas at anchors, clip, filter
    small, NMS (reference: operators/detection/generate_proposals_v2_op).
    Single-image oriented; batches loop."""
    _host_only("generate_proposals", scores, bbox_deltas, img_size)
    sc = np.asarray(_ensure_tensor(scores)._array, np.float32)
    bd = np.asarray(_ensure_tensor(bbox_deltas)._array, np.float32)
    imgs = np.asarray(_ensure_tensor(img_size)._array, np.float32)
    anc = np.asarray(_ensure_tensor(anchors)._array,
                     np.float32).reshape(-1, 4)
    var = np.asarray(_ensure_tensor(variances)._array,
                     np.float32).reshape(-1, 4)
    N, A, H, W = sc.shape
    out_rois, out_num, out_probs = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anc[order], var[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.clip(v[:, 2] * d[:, 2], None, 10)) * aw
        h = np.exp(np.clip(v[:, 3] * d[:, 3], None, 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], -1)
        ih, iw = imgs[n, 0], imgs[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep], s[keep]
        kept = np.asarray(nms(Tensor(jnp.asarray(boxes)),
                              iou_threshold=nms_thresh,
                              scores=Tensor(jnp.asarray(s)))._array)
        kept = kept[:post_nms_top_n]
        out_rois.append(boxes[kept])
        out_probs.append(s[kept])
        out_num.append(len(kept))
    rois = Tensor(jnp.asarray(np.concatenate(out_rois, 0)
                              if out_rois else np.zeros((0, 4))))
    probs = Tensor(jnp.asarray(
        np.concatenate(out_probs, 0).reshape(-1, 1)
        if out_probs else np.zeros((0, 1), np.float32)))
    nums = Tensor(jnp.asarray(np.asarray(out_num, np.int32)))
    if return_rois_num:
        return rois, probs, nums
    return rois, probs


class DeformConv2D:
    """Layer face of deform_conv2d (reference: paddle.vision.ops.
    DeformConv2D). Holds weight/bias; offsets (and v2 mask) are inputs."""

    def __new__(cls, in_channels, out_channels, kernel_size, stride=1,
                padding=0, dilation=1, deformable_groups=1, groups=1,
                weight_attr=None, bias_attr=None):
        from ..nn.layer.layers import Layer, Parameter
        import jax

        kh = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        kw = kernel_size if isinstance(kernel_size, int) else kernel_size[1]

        class _DeformConv2D(Layer):
            def __init__(self):
                super().__init__()
                fan_in = in_channels * kh * kw
                bound = 1.0 / (fan_in ** 0.5)
                key = jax.random.PRNGKey(0)
                self.weight = Parameter(jax.random.uniform(
                    key, (out_channels, in_channels // groups, kh, kw),
                    jnp.float32, -bound, bound))
                self.bias = None if bias_attr is False else Parameter(
                    jnp.zeros((out_channels,), jnp.float32))

            def forward(self, x, offset, mask=None):
                return deform_conv2d(
                    x, offset, self.weight, bias=self.bias, stride=stride,
                    padding=padding, dilation=dilation,
                    deformable_groups=deformable_groups, groups=groups,
                    mask=mask)

        return _DeformConv2D()


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference: vision/ops.py yolo_loss over
    operators/detection/yolov3_loss_op): per-sample sum of box
    location (sigmoid-CE for x/y, L1 for w/h, scaled by 2 - gw*gh),
    objectness (best-matching anchors positive, IoU > ignore_thresh
    ignored) and classification (sigmoid-CE, optional label smoothing).

    TPU-native: fully differentiable jnp — targets are scattered with
    ``.at[].set(mode='drop')`` so zero-area padding boxes vanish
    without host-side control flow, and the whole loss fuses into the
    training step. gt_box is [N, B, 4] (cx, cy, w, h, normalized)."""
    import jax

    xs = _ensure_tensor(x)
    gb = _ensure_tensor(gt_box)
    gl = _ensure_tensor(gt_label)
    gs = _ensure_tensor(gt_score) if gt_score is not None else None
    P = len(anchor_mask)
    A = len(anchors) // 2
    aw_all = jnp.asarray(anchors[0::2], jnp.float32)
    ah_all = jnp.asarray(anchors[1::2], jnp.float32)
    mask_arr = jnp.asarray(anchor_mask, jnp.int32)

    def _f(xa, gbox, glab, *maybe_score):
        N, C, H, W = xa.shape
        assert C == P * (5 + class_num), (C, P, class_num)
        in_w = float(downsample_ratio * W)
        in_h = float(downsample_ratio * H)
        xr = xa.reshape(N, P, 5 + class_num, H, W).astype(jnp.float32)
        tx, ty = xr[:, :, 0], xr[:, :, 1]
        tw, th = xr[:, :, 2], xr[:, :, 3]
        tobj = xr[:, :, 4]
        tcls = xr[:, :, 5:]  # [N, P, class, H, W]
        gbox = gbox.astype(jnp.float32)
        gx, gy = gbox[..., 0], gbox[..., 1]   # [N, B]
        gw, gh = gbox[..., 2], gbox[..., 3]
        valid = (gw > 0) & (gh > 0)

        # best anchor per gt by shape IoU over ALL anchors
        gwp = gw[..., None] * in_w   # [N, B, 1] pixels
        ghp = gh[..., None] * in_h
        inter = jnp.minimum(gwp, aw_all) * jnp.minimum(ghp, ah_all)
        union = gwp * ghp + aw_all * ah_all - inter
        shape_iou = inter / jnp.maximum(union, 1e-9)
        best = jnp.argmax(shape_iou, axis=-1)          # [N, B]
        # responsible slot within this head's anchor_mask (or -1)
        in_mask = best[..., None] == mask_arr          # [N, B, P]
        slot = jnp.where(in_mask.any(-1),
                         jnp.argmax(in_mask, -1), -1)  # [N, B]
        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)
        ok = valid & (slot >= 0)
        n_idx = jnp.broadcast_to(jnp.arange(N)[:, None], gi.shape)
        flat = (((n_idx * P + slot) * H + gj) * W + gi)
        size = N * P * H * W
        # invalid rows get an OUT-OF-BOUNDS POSITIVE sentinel: jax
        # normalizes negative indices (-1 -> size-1) BEFORE mode='drop'
        # applies, which would scatter padding boxes into the last cell
        flat = jnp.where(ok, flat, size)

        bw = aw_all[best] / in_w   # best anchor size, normalized
        bh = ah_all[best] / in_h
        tx_t = gx * W - gi
        ty_t = gy * H - gj
        tw_t = jnp.log(jnp.maximum(gw / jnp.maximum(bw, 1e-9), 1e-9))
        th_t = jnp.log(jnp.maximum(gh / jnp.maximum(bh, 1e-9), 1e-9))
        box_scale = 2.0 - gw * gh
        score = maybe_score[0].astype(jnp.float32) if maybe_score \
            else jnp.ones_like(gx)

        def scat(vals):
            return jnp.zeros(size, jnp.float32).at[flat.reshape(-1)]\
                .set(vals.reshape(-1), mode="drop")\
                .reshape(N, P, H, W)

        m_pos = scat(jnp.ones_like(gx))            # responsible cells
        sx = scat(tx_t)
        sy = scat(ty_t)
        sw = scat(tw_t)
        sh = scat(th_t)
        sscale = scat(box_scale * score)

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))

        loss_xy = (bce(tx, sx) + bce(ty, sy)) * sscale * m_pos
        loss_wh = (jnp.abs(tw - sw) + jnp.abs(th - sh)) \
            * sscale * m_pos

        # objectness: decode pred boxes, IoU vs every gt; > thresh and
        # not responsible -> ignored
        grid_x = jnp.arange(W).reshape(1, 1, 1, W)
        grid_y = jnp.arange(H).reshape(1, 1, H, 1)
        sig = jax.nn.sigmoid
        px = (sig(tx) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_x) / W
        py = (sig(ty) * scale_x_y - 0.5 * (scale_x_y - 1) + grid_y) / H
        paw = aw_all[mask_arr].reshape(1, P, 1, 1)
        pah = ah_all[mask_arr].reshape(1, P, 1, 1)
        pw = jnp.exp(jnp.clip(tw, -10, 10)) * paw / in_w
        ph = jnp.exp(jnp.clip(th, -10, 10)) * pah / in_h

        def box_iou_cwh(px, py, pw, ph, gx, gy, gw, gh):
            # [N,P,H,W] pred vs [N,B] gt -> [N,B,P,H,W]
            px, py, pw, ph = (v[:, None] for v in (px, py, pw, ph))
            gx, gy, gw, gh = (v[..., None, None, None]
                              for v in (gx, gy, gw, gh))
            x1 = jnp.maximum(px - pw / 2, gx - gw / 2)
            y1 = jnp.maximum(py - ph / 2, gy - gh / 2)
            x2 = jnp.minimum(px + pw / 2, gx + gw / 2)
            y2 = jnp.minimum(py + ph / 2, gy + gh / 2)
            inter = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
            return inter / jnp.maximum(pw * ph + gw * gh - inter, 1e-9)

        iou = box_iou_cwh(px, py, pw, ph, gx, gy, gw, gh)
        iou = jnp.where(valid[..., None, None, None], iou, 0.0)
        best_iou = iou.max(axis=1)                      # [N, P, H, W]
        ignore = (best_iou > ignore_thresh) & (m_pos == 0)
        obj_w = jnp.where(ignore, 0.0, 1.0)
        sobj_score = scat(score)
        loss_obj = bce(tobj, m_pos) * obj_w \
            * jnp.where(m_pos > 0, sobj_score, 1.0)

        # classification at responsible cells
        pos = 1.0 - 1.0 / class_num if use_label_smooth and \
            class_num > 1 else 1.0
        neg = 1.0 / class_num if use_label_smooth and class_num > 1 \
            else 0.0
        onehot = jax.nn.one_hot(glab, class_num)        # [N, B, class]
        y = onehot * pos + (1 - onehot) * neg
        # ONE scatter of the whole [B, class] payload (not class_num
        # sequential full-size scatters)
        scls = jnp.zeros((size, class_num), jnp.float32)\
            .at[flat.reshape(-1)].set(y.reshape(-1, class_num),
                                      mode="drop")\
            .reshape(N, P, H, W, class_num)
        scls = jnp.moveaxis(scls, -1, 2)                # [N,P,class,H,W]
        loss_cls = bce(tcls, scls) * m_pos[:, :, None] \
            * sobj_score[:, :, None]

        per_n = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3))
                 + loss_obj.sum((1, 2, 3))
                 + loss_cls.sum((1, 2, 3, 4)))
        return per_n

    args = (xs, gb, gl) + ((gs,) if gs is not None else ())
    return apply_op(_f, *args, op_name="yolo_loss")


class RoIAlign:
    """Layer wrapper over roi_align (reference: vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         spatial_scale=self._spatial_scale,
                         aligned=aligned)


class RoIPool:
    """Layer wrapper over roi_pool (reference: vision/ops.py RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        spatial_scale=self._spatial_scale)


class PSRoIPool:
    """Layer wrapper over psroi_pool (reference: PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          spatial_scale=self._spatial_scale)


# reference: vision/ops.py also exposes the image-io pair
from .io import read_file, decode_jpeg  # noqa: E402,F401
__all__ += ["read_file", "decode_jpeg"]
