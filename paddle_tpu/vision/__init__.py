"""paddle.vision parity surface (models + datasets + transforms + ops)."""
from . import models
from . import transforms
from . import datasets
from . import ops
from . import io
