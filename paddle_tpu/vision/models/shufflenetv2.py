"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ...nn import (Layer, Sequential, Conv2D, BatchNorm2D, ReLU, Swish,
                   MaxPool2D, Linear, AdaptiveAvgPool2D, ChannelShuffle)
from ...tensor.manipulation import concat, flatten, split
from ._utils import load_pretrained

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
}
_STAGE_REPEATS = [4, 8, 4]


def _conv_bn(in_c, out_c, kernel, stride=1, groups=1, act=ReLU):
    layers = [Conv2D(in_c, out_c, kernel, stride=stride,
                     padding=kernel // 2, groups=groups, bias_attr=False),
              BatchNorm2D(out_c)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class InvertedResidual(Layer):
    """Stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, channels, act):
        super().__init__()
        half = channels // 2
        self.branch = Sequential(
            _conv_bn(half, half, 1, act=act),
            _conv_bn(half, half, 3, groups=half, act=None),
            _conv_bn(half, half, 1, act=act))
        self.shuffle = ChannelShuffle(2)

    def forward(self, x):
        x1, x2 = split(x, 2, axis=1)
        return self.shuffle(concat([x1, self.branch(x2)], axis=1))


class InvertedResidualDS(Layer):
    """Stride-2 (downsampling) unit: both branches transform, no split."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        half = out_c // 2
        self.branch1 = Sequential(
            _conv_bn(in_c, in_c, 3, stride=2, groups=in_c, act=None),
            _conv_bn(in_c, half, 1, act=act))
        self.branch2 = Sequential(
            _conv_bn(in_c, half, 1, act=act),
            _conv_bn(half, half, 3, stride=2, groups=half, act=None),
            _conv_bn(half, half, 1, act=act))
        self.shuffle = ChannelShuffle(2)

    def forward(self, x):
        return self.shuffle(concat([self.branch1(x), self.branch2(x)],
                                   axis=1))


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        act_layer = Swish if act == "swish" else ReLU
        outs = _STAGE_OUT[scale]
        self.conv1 = _conv_bn(3, outs[0], 3, stride=2, act=act_layer)
        self.maxpool = MaxPool2D(3, 2, 1)
        blocks = []
        in_c = outs[0]
        for stage, repeats in enumerate(_STAGE_REPEATS):
            out_c = outs[stage + 1]
            blocks.append(InvertedResidualDS(in_c, out_c, act_layer))
            for _ in range(repeats - 1):
                blocks.append(InvertedResidual(out_c, act_layer))
            in_c = out_c
        self.blocks = Sequential(*blocks)
        self.conv_last = _conv_bn(in_c, outs[-1], 1, act=act_layer)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(outs[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=0.25, **kwargs),
                           "shufflenet_v2_x0_25", pretrained)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=0.33, **kwargs),
                           "shufflenet_v2_x0_33", pretrained)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=0.5, **kwargs),
                           "shufflenet_v2_x0_5", pretrained)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=1.0, **kwargs),
                           "shufflenet_v2_x1_0", pretrained)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=1.5, **kwargs),
                           "shufflenet_v2_x1_5", pretrained)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=2.0, **kwargs),
                           "shufflenet_v2_x2_0", pretrained)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return load_pretrained(ShuffleNetV2(scale=1.0, act="swish", **kwargs),
                           "shufflenet_v2_swish", pretrained)
