"""Inception v3 (reference: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ...nn import (Layer, Sequential, Conv2D, BatchNorm2D, ReLU, MaxPool2D,
                   AvgPool2D, Dropout, Linear, AdaptiveAvgPool2D)
from ...tensor.manipulation import concat, flatten
from ._utils import load_pretrained

__all__ = ["InceptionV3", "inception_v3"]


def _conv_bn(in_c, out_c, kernel, stride=1, padding=0):
    return Sequential(
        Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
               bias_attr=False),
        BatchNorm2D(out_c), ReLU())


class InceptionA(Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _conv_bn(in_c, 64, 1)
        self.b5 = Sequential(_conv_bn(in_c, 48, 1),
                             _conv_bn(48, 64, 5, padding=2))
        self.b3 = Sequential(_conv_bn(in_c, 64, 1),
                             _conv_bn(64, 96, 3, padding=1),
                             _conv_bn(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, 1, 1),
                             _conv_bn(in_c, pool_features, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class InceptionB(Layer):  # grid reduction 35→17
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _conv_bn(in_c, 384, 3, stride=2)
        self.b3d = Sequential(_conv_bn(in_c, 64, 1),
                              _conv_bn(64, 96, 3, padding=1),
                              _conv_bn(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class InceptionC(Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _conv_bn(in_c, 192, 1)
        self.b7 = Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, 1, 1), _conv_bn(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class InceptionD(Layer):  # grid reduction 17→8
    def __init__(self, in_c):
        super().__init__()
        self.b3 = Sequential(_conv_bn(in_c, 192, 1),
                             _conv_bn(192, 320, 3, stride=2))
        self.b7 = Sequential(
            _conv_bn(in_c, 192, 1),
            _conv_bn(192, 192, (1, 7), padding=(0, 3)),
            _conv_bn(192, 192, (7, 1), padding=(3, 0)),
            _conv_bn(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class InceptionE(Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _conv_bn(in_c, 320, 1)
        self.b3_stem = _conv_bn(in_c, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = Sequential(_conv_bn(in_c, 448, 1),
                                   _conv_bn(448, 384, 3, padding=1))
        self.b3d_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, 1, 1), _conv_bn(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], 1),
                       concat([self.b3d_a(d), self.b3d_b(d)], 1),
                       self.bp(x)], 1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), MaxPool2D(3, 2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3), MaxPool2D(3, 2))
        self.blocks = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return load_pretrained(InceptionV3(**kwargs), "inception_v3",
                           pretrained)
