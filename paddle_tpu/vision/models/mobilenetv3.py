"""MobileNetV3 (reference: python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from ...nn import (Layer, Sequential, Conv2D, BatchNorm2D, ReLU, Hardswish,
                   Hardsigmoid, Linear, Dropout, AdaptiveAvgPool2D)
from ...tensor.manipulation import flatten
from ._utils import _make_divisible, load_pretrained

__all__ = ["MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large"]


class SqueezeExcitation(Layer):
    """reference: mobilenetv3.py:38."""

    def __init__(self, channels, squeeze_channels):
        super().__init__()
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(channels, squeeze_channels, 1)
        self.fc2 = Conv2D(squeeze_channels, channels, 1)
        self.relu = ReLU()
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.avgpool(x)
        s = self.relu(self.fc1(s))
        s = self.hsig(self.fc2(s))
        return x * s


def _conv_bn_act(in_c, out_c, kernel, stride=1, groups=1, act=None):
    layers = [Conv2D(in_c, out_c, kernel, stride=stride,
                     padding=(kernel - 1) // 2, groups=groups,
                     bias_attr=False),
              BatchNorm2D(out_c)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class InvertedResidual(Layer):
    """reference: mobilenetv3.py:115."""

    def __init__(self, in_c, expanded, out_c, kernel, stride, use_se,
                 use_hs):
        super().__init__()
        act = Hardswish if use_hs else ReLU
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expanded != in_c:
            layers.append(_conv_bn_act(in_c, expanded, 1, act=act))
        layers.append(_conv_bn_act(expanded, expanded, kernel, stride,
                                   groups=expanded, act=act))
        if use_se:
            layers.append(SqueezeExcitation(
                expanded, _make_divisible(expanded // 4)))
        layers.append(_conv_bn_act(expanded, out_c, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3(Layer):
    def __init__(self, cfg, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = lambda ch: _make_divisible(ch * scale)  # noqa: E731
        in_c = c(16)
        blocks = [_conv_bn_act(3, in_c, 3, stride=2, act=Hardswish)]
        for k, exp, out, se, hs, s in cfg:
            blocks.append(InvertedResidual(in_c, c(exp), c(out), k, s,
                                           se, hs))
            in_c = c(out)
        last_conv = 6 * in_c
        blocks.append(_conv_bn_act(in_c, last_conv, 1, act=Hardswish))
        self.features = Sequential(*blocks)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_conv, last_channel), Hardswish(),
                Dropout(0.2), Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


# (kernel, expanded, out, use_se, use_hs, stride)
_SMALL = [
    (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1), (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2), (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1),
]
_LARGE = [
    (3, 16, 16, False, False, 1), (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1), (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1), (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2), (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1), (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1), (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2), (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
]


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, last_channel=1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, last_channel=1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(MobileNetV3Small(scale=scale, **kwargs),
                           f"mobilenet_v3_small_x{float(scale)}", pretrained)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(MobileNetV3Large(scale=scale, **kwargs),
                           f"mobilenet_v3_large_x{float(scale)}", pretrained)
