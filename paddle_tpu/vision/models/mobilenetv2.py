"""MobileNetV2 (reference: python/paddle/vision/models/mobilenetv2.py)."""
from __future__ import annotations

from ...nn import (Layer, Sequential, Conv2D, BatchNorm2D, ReLU6, Linear,
                   Dropout, AdaptiveAvgPool2D)
from ...tensor.manipulation import flatten
from ._utils import _make_divisible, load_pretrained

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _conv_bn_relu6(in_c, out_c, kernel=3, stride=1, groups=1):
    return Sequential(
        Conv2D(in_c, out_c, kernel, stride=stride,
               padding=(kernel - 1) // 2, groups=groups, bias_attr=False),
        BatchNorm2D(out_c), ReLU6())


class InvertedResidual(Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn_relu6(in_c, hidden, 1))
        layers += [
            _conv_bn_relu6(hidden, hidden, 3, stride, groups=hidden),
            Conv2D(hidden, out_c, 1, bias_attr=False),
            BatchNorm2D(out_c),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        blocks = [_conv_bn_relu6(3, in_c, 3, stride=2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                blocks.append(InvertedResidual(in_c, out_c,
                                               s if i == 0 else 1, t))
                in_c = out_c
        blocks.append(_conv_bn_relu6(in_c, last_c, 1))
        self.features = Sequential(*blocks)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(MobileNetV2(scale=scale, **kwargs),
                           f"mobilenetv2_{float(scale)}", pretrained)
