"""GoogLeNet / Inception v1 (reference:
python/paddle/vision/models/googlenet.py)."""
from __future__ import annotations

from ...nn import (Layer, Sequential, Conv2D, ReLU, MaxPool2D,
                   Dropout, Linear, AdaptiveAvgPool2D)
from ...tensor.manipulation import concat, flatten
from ._utils import load_pretrained

__all__ = ["GoogLeNet", "googlenet"]


def _conv_relu(in_c, out_c, kernel, stride=1, padding=0):
    return Sequential(Conv2D(in_c, out_c, kernel, stride=stride,
                             padding=padding), ReLU())


class Inception(Layer):
    """reference: googlenet.py:67."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.branch1 = _conv_relu(in_c, c1, 1)
        self.branch2 = Sequential(_conv_relu(in_c, c3r, 1),
                                  _conv_relu(c3r, c3, 3, padding=1))
        self.branch3 = Sequential(_conv_relu(in_c, c5r, 1),
                                  _conv_relu(c5r, c5, 5, padding=2))
        self.branch4 = Sequential(MaxPool2D(3, 1, 1),
                                  _conv_relu(in_c, proj, 1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class GoogLeNet(Layer):
    """Returns (out, aux1, aux2) like the reference (googlenet.py:107)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _conv_relu(3, 64, 7, stride=2, padding=3), MaxPool2D(3, 2, 1),
            _conv_relu(64, 64, 1), _conv_relu(64, 192, 3, padding=1),
            MaxPool2D(3, 2, 1))
        self.inc3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, 2, 1)
        self.inc4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, 2, 1)
        self.inc5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
            # auxiliary classifiers off inc4a / inc4d; adaptive pooling to
            # the reference's 4x4 aux grid keeps them input-size agnostic
            self.aux_pool1 = AdaptiveAvgPool2D((4, 4))
            self.aux_conv1 = _conv_relu(512, 128, 1)
            self.aux_fc1 = Sequential(Linear(128 * 4 * 4, 1024), ReLU(),
                                      Dropout(0.7), Linear(1024,
                                                           num_classes))
            self.aux_pool2 = AdaptiveAvgPool2D((4, 4))
            self.aux_conv2 = _conv_relu(528, 128, 1)
            self.aux_fc2 = Sequential(Linear(128 * 4 * 4, 1024), ReLU(),
                                      Dropout(0.7), Linear(1024,
                                                           num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        a = self.inc4a(x)
        x = self.inc4c(self.inc4b(a))
        d = self.inc4d(x)
        x = self.pool4(self.inc4e(d))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            out = self.fc(self.dropout(flatten(x, 1)))
            aux1 = self.aux_fc1(flatten(self.aux_conv1(self.aux_pool1(a)),
                                        1))
            aux2 = self.aux_fc2(flatten(self.aux_conv2(self.aux_pool2(d)),
                                        1))
            return out, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    return load_pretrained(GoogLeNet(**kwargs), "googlenet", pretrained)
