"""Shared helpers for the model zoo
(reference: python/paddle/vision/models/_utils.py; pretrained plumbing
analog: python/paddle/vision/models/resnet.py:351-359 +
python/paddle/utils/download.py:73 get_weights_path_from_url)."""
from __future__ import annotations

import os

# arch -> (source url-or-path, md5-or-None). The reference hardcodes
# paddle.org CDN urls per arch; on air-gapped TPU pods artifacts arrive by
# rsync/GCS instead, so the registry starts empty and is seeded either by
# register_pretrained_source() or by dropping "<arch>.pdparams" into
# $PADDLE_TPU_PRETRAINED_HOME (or the WEIGHTS_HOME cache).
PRETRAINED_REGISTRY: dict = {}


def register_pretrained_source(arch: str, url: str, md5sum: str | None = None):
    """Register where ``arch``'s weights live (http(s)/file:// url or a
    local path understood by utils.download.get_weights_path_from_url)."""
    PRETRAINED_REGISTRY[arch] = (url, md5sum)


def _local_candidates(arch: str):
    from ...utils.download import WEIGHTS_HOME
    roots = []
    home = os.environ.get("PADDLE_TPU_PRETRAINED_HOME")
    if home:
        roots.append(home)
    roots.append(WEIGHTS_HOME)
    for root in roots:
        for ext in (".pdparams", ".npz", ".pth", ".pt"):
            yield os.path.join(root, arch + ext)


def _read_state_dict(path: str):
    """Load a raw {name: array} mapping from a weights artifact.
    Returns (state, from_torch) — torch-saved dicts store Linear weights
    (out, in) and need the transpose rule in _compat_keys."""
    import numpy as np
    if os.path.isdir(path):  # archive source: resolve the file inside
        found = [os.path.join(path, f) for f in sorted(os.listdir(path))
                 if f.endswith((".pdparams", ".npz", ".pth", ".pt"))]
        if len(found) != 1:
            raise ValueError(
                f"pretrained archive {path} must contain exactly one "
                f"weights file (.pdparams/.npz/.pth/.pt); found {found}")
        path = found[0]
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}, False
    if path.endswith((".pth", ".pt")):
        import torch
        obj = torch.load(path, map_location="cpu", weights_only=True)
        for wrap in ("state_dict", "model_state_dict", "model"):
            if isinstance(obj, dict) and isinstance(obj.get(wrap), dict):
                obj = obj[wrap]
                break
        bad = [k for k, v in obj.items() if not hasattr(v, "numpy")]
        if bad:
            raise ValueError(
                f"pretrained artifact {path} holds non-tensor entries "
                f"{bad[:4]}; pass a plain state dict (or a checkpoint "
                f"with a 'state_dict' key)")
        return {k: v.numpy() for k, v in obj.items()}, True
    from ...framework.io import load as io_load
    obj = io_load(path)
    if not isinstance(obj, dict):
        raise ValueError(
            f"pretrained artifact {path} did not contain a state dict "
            f"(got {type(obj).__name__})")
    return obj, False


# torch-convention buffer names -> paddle-convention (BatchNorm)
_TORCH_RENAMES = {"running_mean": "_mean", "running_var": "_variance"}
_STRIP_PREFIXES = ("module.", "model.", "backbone.")


def _compat_keys(raw: dict, own: dict, from_torch: bool = False):
    """Name-compat bridge (vision analog of models/convert.py): strip
    wrapper prefixes, rename torch-convention BN buffers, drop torch
    bookkeeping, and transpose 2-D weights saved in (out, in) layout.
    The transpose is format-driven (torch artifacts transpose every 2-D
    .weight, square or not); for paddle-layout dicts only an unambiguous
    shape mismatch triggers it."""
    import numpy as np
    out = {}
    for k, v in raw.items():
        for p in _STRIP_PREFIXES:
            # strip only when it actually bridges to a known name — a
            # model may legitimately own a submodule called e.g.
            # 'backbone'
            if (k.startswith(p) and k not in own
                    and k[len(p):] in own):
                k = k[len(p):]
        head, _, leaf = k.rpartition(".")
        if leaf == "num_batches_tracked":
            continue
        if leaf in _TORCH_RENAMES:
            k = (head + "." if head else "") + _TORCH_RENAMES[leaf]
        arr = np.asarray(getattr(v, "_array", v))
        if k in own and arr.ndim == 2:
            want = tuple(own[k]._array.shape)
            if from_torch and leaf == "weight":
                arr = arr.T  # torch Linear stores (out, in)
            elif (tuple(arr.shape) != want
                    and tuple(arr.shape[::-1]) == want):
                arr = arr.T
        out[k] = arr
    return out


def load_pretrained(model, arch: str, pretrained):
    """Hydrate ``model`` from a pretrained-weights artifact, or raise.

    ``pretrained`` may be False/None (no-op), a path/url string, or True —
    which searches $PADDLE_TPU_PRETRAINED_HOME and the WEIGHTS_HOME cache
    for "<arch>.{pdparams,npz,pth,pt}", then the registered source. The
    reference downloads-or-asserts (resnet.py:351-359); silently returning
    random weights is never acceptable, so a miss raises with the searched
    locations."""
    if not pretrained:
        return model
    if isinstance(pretrained, os.PathLike):
        pretrained = os.fspath(pretrained)
    path = None
    if isinstance(pretrained, str):
        from ...utils.download import get_weights_path_from_url
        path = (pretrained if os.path.exists(pretrained)
                else get_weights_path_from_url(pretrained))
    else:
        searched = []
        for cand in _local_candidates(arch):
            searched.append(cand)
            if os.path.exists(cand):
                path = cand
                break
        if path is None and arch in PRETRAINED_REGISTRY:
            from ...utils.download import get_weights_path_from_url
            url, md5 = PRETRAINED_REGISTRY[arch]
            path = get_weights_path_from_url(url, md5)
        if path is None:
            raise RuntimeError(
                f"{arch}(pretrained=True): no weights artifact found. "
                f"Searched {searched} and the source registry. Seed one "
                f"with register_pretrained_source('{arch}', <url-or-path>)"
                f", drop '{arch}.pdparams' into $PADDLE_TPU_PRETRAINED_"
                f"HOME, or pass pretrained=<path>.")
    own = model.state_dict()
    raw, from_torch = _read_state_dict(path)
    state = _compat_keys(raw, own, from_torch)
    missing = [k for k in own if k not in state]
    if missing:  # refuse BEFORE mutating the caller's model
        raise RuntimeError(
            f"{arch}: pretrained artifact {path} is missing "
            f"{len(missing)} parameters (e.g. {missing[:4]}); refusing a "
            f"partial hydration")
    bad_shapes = [
        (k, tuple(state[k].shape), tuple(own[k]._array.shape))
        for k in own if tuple(state[k].shape) != tuple(own[k]._array.shape)]
    if bad_shapes:  # also before mutating: set_state_dict raises mid-loop
        raise RuntimeError(
            f"{arch}: pretrained artifact {path} has mismatched shapes "
            f"(e.g. {bad_shapes[:3]}); was it saved for a different "
            f"num_classes/width?")
    model.set_state_dict(state)
    return model


def _make_divisible(v, divisor=8, min_value=None):
    """Round channel counts to multiples of `divisor` without dropping more
    than 10% (the MobileNet paper rule; reference _utils.py:22)."""
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v
