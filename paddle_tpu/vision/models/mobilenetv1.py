"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ...nn import (Layer, Sequential, Conv2D, BatchNorm2D, ReLU, Linear,
                   AdaptiveAvgPool2D)
from ...tensor.manipulation import flatten
from ._utils import _make_divisible, load_pretrained

__all__ = ["MobileNetV1", "mobilenet_v1"]


def _conv_bn(in_c, out_c, kernel, stride=1, padding=0, groups=1):
    return Sequential(
        Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
               groups=groups, bias_attr=False),
        BatchNorm2D(out_c), ReLU())


class DepthwiseSeparable(Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.depthwise = _conv_bn(in_c, in_c, 3, stride, 1, groups=in_c)
        self.pointwise = _conv_bn(in_c, out_c, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = lambda ch: _make_divisible(ch * scale)  # noqa: E731
        cfg = [  # (in, out, stride)
            (c(32), c(64), 1), (c(64), c(128), 2), (c(128), c(128), 1),
            (c(128), c(256), 2), (c(256), c(256), 1), (c(256), c(512), 2),
            (c(512), c(512), 1), (c(512), c(512), 1), (c(512), c(512), 1),
            (c(512), c(512), 1), (c(512), c(512), 1), (c(512), c(1024), 2),
            (c(1024), c(1024), 1),
        ]
        blocks = [_conv_bn(3, c(32), 3, stride=2, padding=1)]
        blocks += [DepthwiseSeparable(i, o, s) for i, o, s in cfg]
        self.features = Sequential(*blocks)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(MobileNetV1(scale=scale, **kwargs),
                           f"mobilenetv1_{float(scale)}", pretrained)
