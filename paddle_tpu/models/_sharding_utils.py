"""Shared placement helpers for model train-step builders
(models.llama, models.ernie)."""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["sharding_tree", "replicate_scalars"]


def sharding_tree(mesh, tree_specs):
    """PartitionSpec pytree -> NamedSharding pytree on `mesh`."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree_specs,
        is_leaf=lambda s: isinstance(s, P))


def replicate_scalars(mesh, tree):
    """device_put scalar leaves replicated over the mesh. Optimizer
    states created by jit leave scalars (Adam count) on one device; a
    state tree with inconsistent device assignments is rejected by jit
    once the leaves are committed (e.g. after a checkpoint restore)."""
    def place(x):
        if hasattr(x, "shape") and getattr(x, "ndim", None) == 0:
            return jax.device_put(x, NamedSharding(mesh, P()))
        return x
    return jax.tree_util.tree_map(place, tree)
