"""ERNIE-style encoder pretraining (the BASELINE.json "ERNIE-3.0
pretrain" milestone config).

Reference analog: PaddleNLP's ERNIE (ernie/modeling.py) over this
repo's reference kernels — transformer encoder (post-LN, bidirectional
self-attention with padding mask), MLM head tied to the word embedding,
and the sentence-order/next-sentence head; pretraining objective
MLM + NSP (ERNIE 1.0-style; the 3.0 recipe swaps datasets/task heads,
not the compute graph).

TPU-native: the same stacked-pytree + lax.scan + GSPMD design as
models.llama — layer params carry a leading [L] axis sharded over 'pp',
attention/MLP weights carry the Megatron column/row contract over 'mp',
embeddings are vocab-parallel. Bidirectional attention runs on the
XLA-fused jnp path with an additive padding mask (the Pallas flash
kernel is causal-only; bidirectional flash is a follow-up), so XLA
still fuses softmax into the MXU matmuls.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ErnieConfig", "init_params", "param_specs", "forward_pure",
           "pretrain_loss", "build_pretrain_step"]


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def _split(key, n):
    return list(jax.random.split(key, n))


def init_params(cfg: ErnieConfig, key) -> Dict[str, Any]:
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    ks = _split(key, 12)
    std = 0.02

    def init(k, shape):
        return (jax.random.normal(k, shape) * std).astype(cfg.dtype)

    lk = _split(ks[11], 8)
    layers = {
        "wq": init(lk[0], (L, H, H)), "wk": init(lk[1], (L, H, H)),
        "wv": init(lk[2], (L, H, H)), "wo": init(lk[3], (L, H, H)),
        "w1": init(lk[4], (L, H, I)), "w2": init(lk[5], (L, I, H)),
        "b_q": jnp.zeros((L, H), cfg.dtype),
        "b_k": jnp.zeros((L, H), cfg.dtype),
        "b_v": jnp.zeros((L, H), cfg.dtype),
        "b_o": jnp.zeros((L, H), cfg.dtype),
        "b_1": jnp.zeros((L, I), cfg.dtype),
        "b_2": jnp.zeros((L, H), cfg.dtype),
        "ln1_w": jnp.ones((L, H), cfg.dtype),
        "ln1_b": jnp.zeros((L, H), cfg.dtype),
        "ln2_w": jnp.ones((L, H), cfg.dtype),
        "ln2_b": jnp.zeros((L, H), cfg.dtype),
    }
    return {
        "word_emb": init(ks[0], (cfg.vocab_size, H)),
        "pos_emb": init(ks[1], (cfg.max_position_embeddings, H)),
        "type_emb": init(ks[2], (cfg.type_vocab_size, H)),
        "emb_ln_w": jnp.ones((H,), cfg.dtype),
        "emb_ln_b": jnp.zeros((H,), cfg.dtype),
        "layers": layers,
        "pooler_w": init(ks[3], (H, H)),
        "pooler_b": jnp.zeros((H,), cfg.dtype),
        "mlm_trans_w": init(ks[4], (H, H)),
        "mlm_trans_b": jnp.zeros((H,), cfg.dtype),
        "mlm_ln_w": jnp.ones((H,), cfg.dtype),
        "mlm_ln_b": jnp.zeros((H,), cfg.dtype),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), cfg.dtype),
        "nsp_w": init(ks[5], (H, 2)),
        "nsp_b": jnp.zeros((2,), cfg.dtype),
    }


def param_specs(cfg: ErnieConfig) -> Dict[str, Any]:
    """Megatron TP contract over 'mp' + layer-stack axis over 'pp'
    (fleet/meta_parallel/mp_layers analog, same as models.llama)."""
    col, row = P("pp", None, "mp"), P("pp", "mp", None)
    vec, vec_mp = P("pp", None), P("pp", "mp")
    layers = {
        "wq": col, "wk": col, "wv": col, "wo": row,
        "w1": col, "w2": row,
        "b_q": vec_mp, "b_k": vec_mp, "b_v": vec_mp, "b_o": vec,
        "b_1": vec_mp, "b_2": vec,
        "ln1_w": vec, "ln1_b": vec, "ln2_w": vec, "ln2_b": vec,
    }
    return {
        "word_emb": P("mp", None),        # vocab parallel
        "pos_emb": P(None, None),
        "type_emb": P(None, None),
        "emb_ln_w": P(None), "emb_ln_b": P(None),
        "layers": layers,
        "pooler_w": P(None, "mp"), "pooler_b": P("mp"),
        "mlm_trans_w": P(None, "mp"), "mlm_trans_b": P("mp"),
        "mlm_ln_w": P(None), "mlm_ln_b": P(None),
        "mlm_bias": P("mp"),
        "nsp_w": P(None, None), "nsp_b": P(None),
    }


def _ln(x, w, b, eps):
    # statistics in fp32 regardless of model dtype (bf16 mantissa is too
    # coarse for post-residual variance — same rationale as llama's
    # _rms_norm upcast)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - mu) / jnp.sqrt(var + eps)).astype(x.dtype)) * w + b


def _encoder_layer(cfg: ErnieConfig, lp, x, mask_bias):
    B, S, H = x.shape
    nh, d = cfg.num_attention_heads, cfg.head_dim
    q = (x @ lp["wq"] + lp["b_q"]).reshape(B, S, nh, d)
    k = (x @ lp["wk"] + lp["b_k"]).reshape(B, S, nh, d)
    v = (x @ lp["wv"] + lp["b_v"]).reshape(B, S, nh, d)
    logits = jnp.einsum("bsnd,btnd->bnst", q, k) / math.sqrt(d)
    logits = logits + mask_bias  # [B, 1, 1, S] additive padding mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(x.dtype)
    ctx = jnp.einsum("bnst,btnd->bsnd", probs, v).reshape(B, S, H)
    attn = ctx @ lp["wo"] + lp["b_o"]
    x = _ln(x + attn, lp["ln1_w"], lp["ln1_b"], cfg.layer_norm_eps)
    mlp = jax.nn.gelu(x @ lp["w1"] + lp["b_1"]) @ lp["w2"] + lp["b_2"]
    return _ln(x + mlp, lp["ln2_w"], lp["ln2_b"], cfg.layer_norm_eps)


def forward_pure(cfg: ErnieConfig, params, input_ids,
                 token_type_ids=None, attention_mask=None):
    """ids -> (sequence_output [B,S,H], pooled_output [B,H])."""
    B, S = input_ids.shape
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    if attention_mask is None:
        attention_mask = jnp.ones((B, S), jnp.int32)
    x = (jnp.take(params["word_emb"], input_ids, axis=0)
         + params["pos_emb"][None, :S]
         + jnp.take(params["type_emb"], token_type_ids, axis=0))
    x = _ln(x, params["emb_ln_w"], params["emb_ln_b"], cfg.layer_norm_eps)
    mask_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                          -1e9).astype(x.dtype)

    def body(carry, lp):
        return _encoder_layer(cfg, lp, carry, mask_bias), None

    x, _ = lax.scan(body, x, params["layers"])
    pooled = jnp.tanh(x[:, 0] @ params["pooler_w"] + params["pooler_b"])
    return x, pooled


def pretrain_loss(cfg: ErnieConfig, params, batch):
    """MLM + NSP/SOP loss.

    batch: input_ids, token_type_ids, attention_mask, nsp_labels [B],
    and EITHER
      masked_positions [B, P] + masked_labels [B, P] (-1 pads) —
      the reference's pretraining input format: the MLM head runs only
      on the ~15% predicted positions, shrinking the dominant [.., V]
      fp32 activation by ~1/mask_rate;
    OR mlm_labels [B, S] (-1 on unpredicted positions) — the dense
      fallback for simple callers.
    """
    seq, pooled = forward_pure(
        cfg, params, batch["input_ids"], batch.get("token_type_ids"),
        batch.get("attention_mask"))
    if "masked_positions" in batch:
        pos = batch["masked_positions"]          # [B, P]
        labels = batch["masked_labels"]          # [B, P], -1 padded
        sel = jnp.take_along_axis(
            seq, jnp.maximum(pos, 0)[..., None], axis=1)  # [B, P, H]
    else:
        labels = batch["mlm_labels"]             # [B, S]
        sel = seq
    h = jax.nn.gelu(sel @ params["mlm_trans_w"] + params["mlm_trans_b"])
    h = _ln(h, params["mlm_ln_w"], params["mlm_ln_b"], cfg.layer_norm_eps)
    logits = (h @ params["word_emb"].T + params["mlm_bias"]).astype(
        jnp.float32)  # tied decoder
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, safe[..., None], -1)[..., 0]
    mlm = jnp.sum(jnp.where(valid, lse - tgt, 0.0)) / \
        jnp.maximum(jnp.sum(valid), 1)
    nsp_logits = (pooled @ params["nsp_w"] + params["nsp_b"]).astype(
        jnp.float32)
    nsp_lse = jax.nn.logsumexp(nsp_logits, axis=-1)
    nsp_tgt = jnp.take_along_axis(
        nsp_logits, batch["nsp_labels"][:, None], -1)[:, 0]
    nsp = jnp.mean(nsp_lse - nsp_tgt)
    return mlm + nsp, {"mlm": mlm, "nsp": nsp}


def build_pretrain_step(cfg: ErnieConfig, topo, optimizer=None):
    """jit'd GSPMD pretrain step over the hybrid mesh (dp x mp; the
    encoder reuses the pp-ready stacked layout but v1 keeps the whole
    stack per device — ERNIE-base depth rarely needs pp)."""
    import optax
    from ._sharding_utils import sharding_tree, replicate_scalars
    mesh = topo.mesh
    opt = optimizer or optax.adamw(1e-4, b1=0.9, b2=0.999,
                                   weight_decay=0.01)
    specs = param_specs(cfg)
    param_sh = sharding_tree(mesh, specs)

    def init_fn(rng):
        with mesh:
            params = jax.jit(lambda k: init_params(cfg, k),
                             out_shardings=param_sh)(rng)
            opt_state = replicate_scalars(mesh, jax.jit(opt.init)(params))
        return params, opt_state

    def step(params, opt_state, batch):
        (total, parts), grads = jax.value_and_grad(
            lambda p: pretrain_loss(cfg, p, batch), has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {"loss": total, **parts}
        return params, opt_state, metrics

    data_sh = NamedSharding(mesh, P("dp", None))
    vec_sh = NamedSharding(mesh, P("dp"))
    _jits: Dict[Any, Any] = {}

    def step_fn(params, opt_state, batch):
        # the compiled contract needs every key; default the optional
        # ones the way pretrain_loss would. One jit specialization per
        # batch-key set (dense mlm_labels vs masked_positions format).
        ids = batch["input_ids"]
        batch = dict(batch)
        batch.setdefault("token_type_ids", jnp.zeros_like(ids))
        batch.setdefault("attention_mask", jnp.ones_like(ids))
        keys = frozenset(batch)
        step_jit = _jits.get(keys)
        if step_jit is None:
            batch_sh = {k: (vec_sh if batch[k].ndim == 1 else data_sh)
                        for k in batch}
            step_jit = jax.jit(step,
                               in_shardings=(param_sh, None, batch_sh),
                               out_shardings=(param_sh, None, None),
                               donate_argnums=(0, 1))
            _jits[keys] = step_jit
        with mesh:
            return step_jit(params, opt_state, batch)
    return step_fn, init_fn
