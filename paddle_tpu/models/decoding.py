"""Autoregressive decoding with KV caches — the inference half of the
model families.

Reference analog: the fused inference transformer stack
(paddle/fluid/operators/fused/fused_multi_transformer_op.cu — per-layer
KV cache updated in place, one token per step) and PaddleNLP's
generate() loop. TPU-native shape: the cache is a stacked [L, B, S_max,
kv_heads, head_dim] pair updated with lax.dynamic_update_slice inside a
jit-compiled step; the whole decode loop is one lax.scan, so the chip
never returns to the host between tokens. Prefill processes the prompt
as a single chunk (same code path, T=prompt_len), matching how the
reference separates context-encode from decode phases.

Model-agnostic core: cached_attention_core() attends new-chunk queries
over the cache; each model family computes its own q/k/v (rope or
learned positions) and MLP around it.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["KVCache", "init_kv_cache", "cached_attention_core",
           "sample_logits", "generate_tokens", "model_generate"]


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S_max, n_kv_heads, head_dim]
    v: jnp.ndarray


def init_kv_cache(num_layers, batch, max_len, n_kv_heads, head_dim,
                  dtype=jnp.bfloat16) -> KVCache:
    shape = (num_layers, batch, max_len, n_kv_heads, head_dim)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


_NEG_BIG = -1e30  # finite mask: -inf would NaN a fully-masked row


def cached_attention_core(q, k_new, v_new, cache_k, cache_v, pos,
                          lengths=None):
    """q/k_new/v_new: [B, T, h, d] for the current chunk starting at
    ``pos`` (traced scalar); cache_k/v: [B, S_max, kv_h, d] for one
    layer. Returns (out [B, T, h, d], new_ck, new_cv).
    GQA: q is viewed as [B, T, kv_h, rep, d] and contracted directly
    against the kv-width cache — the K/V tensors are never expanded to
    q-head width (the memory that matters at long context).

    ``lengths`` (optional, [B] int32): total valid kv length per row
    including the current chunk; defaults to ``pos + T``.  Cache
    positions at or past it are masked EXPLICITLY — correctness must
    not rest on the causal mask happening to cover the unwritten
    (zero) tail of the cache, and per-row lengths are what a ragged
    serving batch needs."""
    B, T, nh, d = q.shape
    S_max = cache_k.shape[1]
    nkv = cache_k.shape[2]
    cache_k = lax.dynamic_update_slice(
        cache_k, k_new.astype(cache_k.dtype), (0, pos, 0, 0))
    cache_v = lax.dynamic_update_slice(
        cache_v, v_new.astype(cache_v.dtype), (0, pos, 0, 0))
    scale = 1.0 / (d ** 0.5)
    q_pos = pos + jnp.arange(T)
    key_pos = jnp.arange(S_max)
    kv_len = jnp.broadcast_to(
        jnp.asarray(pos + T if lengths is None else lengths,
                    jnp.int32), (B,))
    mask = ((key_pos[None, None, :] <= q_pos[None, :, None])
            & (key_pos[None, None, :] < kv_len[:, None, None]))
    rep = nh // nkv
    # q head h attends kv head h // rep (the jnp.repeat layout)
    qg = q.reshape(B, T, nkv, rep, d).astype(jnp.float32)
    kf = cache_k.astype(jnp.float32)
    vf = cache_v.astype(jnp.float32)
    logits = jnp.einsum("btkrd,bskd->bkrts", qg, kf) * scale
    logits = jnp.where(mask[:, None, None], logits, _NEG_BIG)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkrts,bskd->btkrd", probs, vf)
    return (out.reshape(B, T, nh, d).astype(q.dtype),
            cache_k, cache_v)


def sample_logits(logits, temperature: float, top_k: int, rng):
    """logits: [B, V] fp32. temperature==0 -> greedy; else softmax sample
    with optional top-k filtering. temperature/top_k are trace-time
    constants (python numbers)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k and top_k > 0:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


# compiled generate loops, keyed by (model key, shapes, sampling config)
# so repeated generate() calls with the same signature reuse the
# executable instead of retracing prefill + scan every time
_RUN_CACHE: dict = {}


def generate_tokens(forward_with_cache: Callable, params, input_ids,
                    cache: KVCache, max_new_tokens: int,
                    temperature: float = 0.0, top_k: int = 0,
                    rng=None, eos_token_id: Optional[int] = None,
                    cache_key=None):
    """Shared generate loop: prefill the prompt, then lax.scan one token
    at a time. ``forward_with_cache(params, tokens[B,T], cache, pos) ->
    (logits[B,T,V] fp32, cache)``. Returns [B, max_new_tokens] int32;
    positions after eos are filled with eos. Pass a hashable
    ``cache_key`` identifying the model/config so the compiled loop is
    reused across calls (model_generate does)."""
    if rng is None:
        rng = jax.random.PRNGKey(0)
    B, P = input_ids.shape

    key = (cache_key if cache_key is not None else id(forward_with_cache),
           B, P, int(max_new_tokens), float(temperature), int(top_k),
           eos_token_id, cache.k.shape, str(cache.k.dtype))
    run = _RUN_CACHE.get(key)
    if run is None:
        def run_impl(params, input_ids, cache, rng):
            logits, cache = forward_with_cache(params, input_ids, cache, 0)
            rng, sub = jax.random.split(rng)
            tok = sample_logits(logits[:, -1], temperature, top_k, sub)
            finished = jnp.zeros((B,), jnp.bool_)
            if eos_token_id is not None:
                finished = tok == eos_token_id

            def body(carry, _):
                cache, tok, pos, rng, finished = carry
                logits, cache = forward_with_cache(params, tok[:, None],
                                                   cache, pos)
                rng, sub = jax.random.split(rng)
                nxt = sample_logits(logits[:, 0], temperature, top_k, sub)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                return (cache, nxt, pos + 1, rng, finished), nxt

            (cache, _, _, _, _), rest = lax.scan(
                body, (cache, tok, jnp.int32(P), rng, finished), None,
                length=max_new_tokens - 1)
            return jnp.concatenate(
                [tok[:, None], rest.T.astype(jnp.int32)], axis=1)

        run = jax.jit(run_impl)
        _RUN_CACHE[key] = run
    return run(params, input_ids, cache, rng)


class GenerationMixin:
    """Layer-facade generate(): set ``_generate_fn`` to the family's
    functional generate (cfg, params, ids, ...) and inherit."""

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, seed=0, eos_token_id=None):
        import numpy as np

        from ..core.tensor import Tensor

        ids = np.asarray(input_ids._array
                         if isinstance(input_ids, Tensor) else input_ids)
        fn = type(self)._generate_fn
        out = fn(self.config, self._tree(), jnp.asarray(ids),
                 max_new_tokens, temperature=temperature, top_k=top_k,
                 rng=jax.random.PRNGKey(seed), eos_token_id=eos_token_id)
        return Tensor(out)


def model_generate(forward_with_cache: Callable, *, num_layers: int,
                   kv_heads: int, head_dim: int, max_positions: int,
                   cache_dtype, cache_key, params, input_ids,
                   max_new_tokens: int, temperature: float = 0.0,
                   top_k: int = 0, rng=None,
                   eos_token_id: Optional[int] = None):
    """The one generate() wrapper every model family shares: bounds
    check against the positional-embedding budget, cache allocation at
    kv-head width, memoized compiled loop."""
    B, P = input_ids.shape
    max_len = P + max_new_tokens
    if max_len > max_positions:
        raise ValueError(
            f"prompt {P} + max_new_tokens {max_new_tokens} exceeds "
            f"max_position_embeddings {max_positions}")
    cache = init_kv_cache(num_layers, B, max_len, kv_heads, head_dim,
                          dtype=cache_dtype)
    return generate_tokens(forward_with_cache, params,
                           jnp.asarray(input_ids), cache, max_new_tokens,
                           temperature=temperature, top_k=top_k, rng=rng,
                           eos_token_id=eos_token_id,
                           cache_key=cache_key)
