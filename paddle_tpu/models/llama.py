"""Llama model family — the flagship LLM config (BASELINE.json #4).

Two faces:

1. `LlamaForCausalLM` — an eager `nn.Layer` built from the TP layer library
   (fleet mp_layers), usable like the reference PaddleNLP model: forward,
   loss, generate-one-step. Capability parity surface.

2. The functional core (`init_params` / `forward_pure` /
   `build_train_step`) — pure jnp functions over a stacked-parameter
   pytree, which is what the 4-D+ parallel trainer, the pipeline schedule,
   `__graft_entry__.dryrun_multichip` and `bench.py` drive. This is the
   TPU-native replacement for fleet's PipelineLayer/LayerDesc partitioning
   (reference: fleet/meta_parallel/parallel_layers/pp_layers.py:209) —
   layers are stacked along a leading axis and sharded/scanned rather than
   partitioned into per-rank Python objects.

Parallelism mapping (SURVEY.md §7):
  dp      — batch axis sharding (+ ZeRO: optimizer state sharded on dp)
  mp (tp) — megatron column/row specs on attention + MLP weights; vocab-
            parallel embedding & lm_head; sequence-parallel activations
            ride the same axis between blocks
  pp      — layer-stack axis sharded over 'pp'; GPipe/1F1B microbatch
            schedule via shard_map + ppermute (distributed/pipeline.py)
  ep      — MoE expert axis sharded over 'dp' (GShard-style dense dispatch,
            reference analog: incubate/distributed/models/moe/moe_layer.py)
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P, NamedSharding

from .decoding import GenerationMixin

__all__ = ["LlamaConfig", "LlamaForCausalLM", "init_params", "forward_pure",
           "forward_with_cache", "forward_paged", "build_train_step",
           "param_specs", "PRESETS", "preset", "quantize_params"]


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # MoE (config #5 — DeepSeekMoE/Qwen-MoE shape)
    moe_num_experts: int = 0          # 0 => dense FFN
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # training
    use_remat: bool = True
    # remat policy: "full" recomputes everything (min memory);
    # "dots" saves matmul outputs and recomputes only elementwise chains
    # (near-zero extra FLOPs — the right default when activations fit)
    remat_policy: str = "dots"
    # fused decoder-block Pallas kernels (ops.pallas_ops
    # fused_attention_block / fused_mlp_block): None follows
    # FLAGS_tpu_fused_blocks; "auto" = TPU-only, "on" = wherever the
    # kernels can run (incl. the interpreter — what parity tests use),
    # "off" = always the unfused composition
    fused_blocks: Any = None
    # int8 weight path for serving (quantize_params + the pallas_ops
    # int8_matmul kernels): None follows FLAGS_tpu_quantized; "auto" =
    # quantize weights on TPU only, "on" = everywhere (CPU runs the jnp
    # dequant oracle — same math, what parity tests use), "off" = dense
    quantized: Any = None

    def __post_init__(self):
        assert self.remat_policy in ("full", "dots"), \
            f"remat_policy must be 'full' or 'dots', got " \
            f"{self.remat_policy!r}"
        assert self.fused_blocks in (None, "auto", "on", "off"), \
            f"fused_blocks must be None, 'auto', 'on' or 'off', got " \
            f"{self.fused_blocks!r}"
        assert self.quantized in (None, "auto", "on", "off"), \
            f"quantized must be None, 'auto', 'on' or 'off', got " \
            f"{self.quantized!r}"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


# Named shapes for tools (bench presets, tools/pod_report.py). The
# LlamaConfig defaults ARE the 7B shape, so llama7b overrides nothing.
PRESETS: Dict[str, Dict[str, Any]] = {
    "llama7b": {},
    "llama1b": dict(hidden_size=2048, intermediate_size=5504,
                    num_hidden_layers=16, num_attention_heads=16,
                    num_key_value_heads=16),
    "llama-debug": dict(vocab_size=256, hidden_size=64,
                        intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=4,
                        max_position_embeddings=256),
}


def preset(name: str, **overrides) -> LlamaConfig:
    """LlamaConfig from a named preset, with field overrides on top."""
    if name not in PRESETS:
        raise KeyError(f"unknown llama preset {name!r}; "
                       f"available: {sorted(PRESETS)}")
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return LlamaConfig(**kw)


def _split_key(key, n):
    return list(jax.random.split(key, n))


def init_params(cfg: LlamaConfig, key) -> Dict[str, Any]:
    """Stacked parameter pytree. Layer axis L leads every per-layer array."""
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    V = cfg.vocab_size
    KV = cfg.num_key_value_heads * cfg.head_dim
    k = iter(_split_key(key, 16))
    std = 0.02

    def init(k_, shape):
        return (jax.random.normal(k_, shape, jnp.float32) * std).astype(
            cfg.dtype)

    params = {
        "embed": init(next(k), (V, H)),
        "layers": {
            "ln1": jnp.ones((L, H), cfg.dtype),
            "wq": init(next(k), (L, H, H)),
            "wk": init(next(k), (L, H, KV)),
            "wv": init(next(k), (L, H, KV)),
            "wo": init(next(k), (L, H, H)),
            "ln2": jnp.ones((L, H), cfg.dtype),
        },
        "norm_f": jnp.ones((H,), cfg.dtype),
        "lm_head": init(next(k), (H, V)),
    }
    if cfg.moe_num_experts > 0:
        E = cfg.moe_num_experts
        params["layers"]["router"] = init(next(k), (L, H, E)).astype(
            jnp.float32)
        params["layers"]["w_gate"] = init(next(k), (L, E, H, I))
        params["layers"]["w_up"] = init(next(k), (L, E, H, I))
        params["layers"]["w_down"] = init(next(k), (L, E, I, H))
    else:
        params["layers"]["w_gate"] = init(next(k), (L, H, I))
        params["layers"]["w_up"] = init(next(k), (L, H, I))
        params["layers"]["w_down"] = init(next(k), (L, I, H))
    return params


def param_specs(cfg: LlamaConfig) -> Dict[str, Any]:
    """GSPMD PartitionSpecs — the Column/RowParallel + vocab-parallel and
    expert-parallel placement contract (mp_layers.py analog). Leading layer
    axis is sharded over 'pp' (the pipeline placement)."""
    moe = cfg.moe_num_experts > 0
    layers = {
        "ln1": P("pp", None),
        "wq": P("pp", None, "mp"),     # column parallel
        "wk": P("pp", None, "mp"),
        "wv": P("pp", None, "mp"),
        "wo": P("pp", "mp", None),     # row parallel
        "ln2": P("pp", None),
    }
    if moe:
        layers.update({
            "router": P("pp", None, None),
            "w_gate": P("pp", "dp", None, "mp"),   # experts over dp (=ep)
            "w_up": P("pp", "dp", None, "mp"),
            "w_down": P("pp", "dp", "mp", None),
        })
    else:
        layers.update({
            "w_gate": P("pp", None, "mp"),
            "w_up": P("pp", None, "mp"),
            "w_down": P("pp", "mp", None),
        })
    return {
        "embed": P("mp", None),        # vocab parallel
        "layers": layers,
        "norm_f": P(None),
        "lm_head": P(None, "mp"),      # column parallel (vocab out)
    }


# ---------------------------------------------------------------------------
# pure forward pieces
# ---------------------------------------------------------------------------

def _rope_tables(cfg: LlamaConfig, seq_len: int):
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (cfg.rope_theta
                      ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                      # [S, half]
    emb = jnp.concatenate([freqs, freqs], axis=-1)      # [S, D]
    return jnp.sin(emb), jnp.cos(emb)


def _apply_rope(x, sin, cos):
    # x: [B, S, H, D] (neox style)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    sin_ = sin[None, :, None, :].astype(x.dtype)
    cos_ = cos[None, :, None, :].astype(x.dtype)
    return x * cos_ + rot * sin_


def _rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(ms + eps)).astype(x.dtype) * w


def _attention(cfg: LlamaConfig, lp, x, sin, cos, cp_mesh=None,
               cp_axis="sp", cp_axis_level=False):
    B, S, H = x.shape
    nh, nkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, \
        cfg.head_dim
    q = _qmm(x, lp["wq"]).reshape(B, S, nh, d)
    k = _qmm(x, lp["wk"]).reshape(B, S, nkv, d)
    v = _qmm(x, lp["wv"]).reshape(B, S, nkv, d)
    q = _apply_rope(q, sin, cos)
    k = _apply_rope(k, sin, cos)
    if cp_axis_level:
        # already inside a shard_map that maps cp_axis (the pipeline's
        # pp x sp region): call the axis-level ring directly — nesting
        # another shard_map here would be illegal
        from ..distributed.sequence_parallel import ring_attention
        out = ring_attention(q, k, v, axis_name=cp_axis)
    elif cp_mesh is not None:
        # context parallel: sequence sharded over cp_axis, K/V blocks
        # rotate the ring (distributed.sequence_parallel) — exact causal
        # attention at O(S/n) memory per device. GQA expansion happens
        # inside the ring's block compute, so only nkv heads rotate.
        from ..distributed.sequence_parallel import ring_attention_sharded
        out = ring_attention_sharded(q, k, v, cp_mesh, cp_axis)
    else:
        if nkv != nh:  # grouped-query attention: repeat kv heads
            rep = nh // nkv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        # flash-attention via Pallas when available; jnp fallback
        from ..ops import pallas_ops
        out = pallas_ops.causal_attention(q, k, v)
    return _qmm(out.reshape(B, S, H), lp["wo"])


def _dense_mlp(lp, x):
    gate = jax.nn.silu(_qmm(x, lp["w_gate"]))
    up = _qmm(x, lp["w_up"])
    return _qmm(gate * up, lp["w_down"])


# ---------------------------------------------------------------------------
# int8 weight path (serving): quantize_params + _qmm dispatch
# ---------------------------------------------------------------------------

def _qmm(x, w):
    """x @ w where ``w`` is either a dense array or a quantize_params
    leaf ``{"q": int8 [K, N], "scale": f32 [1, N]}`` — the int8 leaf
    routes through ops.pallas_ops.int8_matmul (Pallas kernel on TPU,
    jnp dequant oracle elsewhere)."""
    if isinstance(w, dict):
        from ..ops.pallas_ops import int8_matmul
        return int8_matmul(x, w["q"], w["scale"])
    return x @ w


def _quantized_mode(cfg: LlamaConfig) -> bool:
    """Resolved int8-weight policy: cfg.quantized, else
    FLAGS_tpu_quantized. "auto" engages on TPU only (CPU keeps dense
    weights — the jnp oracle exists for parity, not speed); "on"
    quantizes everywhere including CPU (what parity tests use); "off"
    never quantizes."""
    from ..ops import pallas_ops
    mode = cfg.quantized
    if mode is None:
        try:
            from ..core.flags import flag
            mode = flag("FLAGS_tpu_quantized")
        except Exception:
            mode = "auto"
    if mode == "off":
        return False
    if mode == "auto" and not pallas_ops._on_tpu():
        return False
    return True


# weight leaves quantize_params converts (per-layer stacked [L, K, N]);
# norms, embed and the MoE expert einsum weights stay dense
_QUANT_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _note_quant_err(name, w, q, scale):
    """Numerics-watchdog gauges (satellite of the int8 path): rms +
    absmax of (dequant - reference) per weight, plus the worst layer
    index for stacked weights — so a bad scale is localized like a NaN.
    All behind FLAGS_tpu_check_nan_inf; zero cost when off."""
    from ..profiler import numerics
    if not numerics.enabled():
        return
    wf = np.asarray(jax.device_get(w)).astype(np.float32)
    deq = (np.asarray(jax.device_get(q)).astype(np.float32)
           * np.asarray(jax.device_get(scale)).astype(np.float32))
    err = deq - wf
    if err.size == 0:
        return
    numerics.note(f"quant_err_rms_{name}",
                  float(np.sqrt(np.mean(err * err))))
    numerics.note(f"quant_err_absmax_{name}", float(np.max(np.abs(err))))
    if err.ndim == 3:  # stacked [L, K, N]: localize the worst layer
        per_layer = np.max(np.abs(err), axis=(1, 2))
        numerics.note(f"quant_err_worst_layer_{name}",
                      float(np.argmax(per_layer)))


def quantize_params(cfg: LlamaConfig, params):
    """PTQ the serving weight path to int8: each matmul weight in
    _QUANT_WEIGHTS (stacked [L, K, N]) plus lm_head becomes a
    ``{"q": int8, "scale": f32}`` leaf via per-output-channel absmax
    (ops.pallas_ops.quantize_int8). lax.scan slices dict leaves along
    the leading L axis like any pytree, so forward bodies see per-layer
    ``{"q": [K, N], "scale": [1, N]}`` and dispatch through _qmm.
    Dense configs only — MoE expert weights ride einsums and stay
    dense. Idempotent (already-quantized leaves pass through)."""
    from ..ops.pallas_ops import quantize_int8
    out = dict(params)
    layers = dict(params["layers"])
    if cfg.moe_num_experts == 0:
        for nm in _QUANT_WEIGHTS:
            w = layers.get(nm)
            if w is None or isinstance(w, dict):
                continue
            q, scale = quantize_int8(w)
            layers[nm] = {"q": q, "scale": scale}
            _note_quant_err(nm, w, q, scale)
    out["layers"] = layers
    head = out.get("lm_head")
    if head is not None and not isinstance(head, dict):
        q, scale = quantize_int8(head)
        out["lm_head"] = {"q": q, "scale": scale}
        _note_quant_err("lm_head", head, q, scale)
    return out


def _moe_mlp(cfg: LlamaConfig, lp, x):
    """GShard top-k MoE with capacity, dense dispatch einsums.

    Reference analog: moe_layer.py:260 MoELayer + global_scatter/gather
    NCCL all-to-all. Here dispatch/combine are einsums against a one-hot
    capacity tensor; with the expert axis of w_* sharded over 'dp', GSPMD
    lowers the token<->expert resharding to the same all-to-all over ICI.
    """
    B, S, H = x.shape
    E, K = cfg.moe_num_experts, cfg.moe_top_k
    T = B * S
    C = max(1, int(cfg.moe_capacity_factor * T * K / E))
    xt = x.reshape(T, H)
    logits = (xt.astype(jnp.float32) @ lp["router"])        # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, K)               # [T, K]
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)
    # position of each (t, k) within its expert's capacity buffer
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)   # [T, K, E]
    flat = onehot.reshape(T * K, E)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(T, K, E)
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)          # [T, K]
    keep = pos < C
    # dispatch tensor [T, K, E, C]
    disp = (onehot.astype(jnp.bool_)
            & keep[..., None]).astype(x.dtype)[..., None] \
        * jax.nn.one_hot(jnp.where(keep, pos, 0), C, dtype=x.dtype)[
            :, :, None, :]
    combine = disp * gate_vals[..., None, None].astype(x.dtype)
    disp2 = disp.sum(1)                                     # [T, E, C]
    expert_in = jnp.einsum("tec,th->ech", disp2, xt)        # [E, C, H]
    gate = jax.nn.silu(jnp.einsum("ech,ehi->eci", expert_in, lp["w_gate"]))
    up = jnp.einsum("ech,ehi->eci", expert_in, lp["w_up"])
    expert_out = jnp.einsum("eci,eih->ech", gate * up, lp["w_down"])
    out = jnp.einsum("tkec,ech->th", combine, expert_out)
    # aux load-balancing loss (GShard)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32),
                  axis=0)
    aux = E * jnp.sum(me * ce)
    return out.reshape(B, S, H), aux


def _fused_block_modes(cfg: LlamaConfig, x, cp_mesh, cp_axis_level):
    """(use_fused_attention, use_fused_mlp) — resolved at trace time from
    the policy (cfg.fused_blocks, else FLAGS_tpu_fused_blocks) and shape
    eligibility. "auto" engages only on real TPU (never the CPU jnp path
    a test traces); "on" engages wherever the kernels can run, including
    the Pallas interpreter — which is how parity tests exercise this."""
    from ..ops import pallas_ops
    mode = cfg.fused_blocks
    if mode is None:
        try:
            from ..core.flags import flag
            mode = flag("FLAGS_tpu_fused_blocks")
        except Exception:
            mode = "auto"
    if mode == "off":
        return False, False
    if mode == "auto" and not pallas_ops._on_tpu():
        return False, False
    attn_ok = (cp_mesh is None and not cp_axis_level
               and cfg.num_key_value_heads == cfg.num_attention_heads
               and pallas_ops.fused_attention_available(
                   x.shape, cfg.head_dim, x.dtype))
    mlp_ok = (cfg.moe_num_experts == 0
              and pallas_ops.fused_mlp_available(
                  x.shape, cfg.intermediate_size, x.dtype))
    return attn_ok, mlp_ok


def decoder_layer(cfg: LlamaConfig, lp, x, sin, cos, cp_mesh=None,
                  cp_axis="sp", cp_axis_level=False):
    """One decoder block on a per-layer param slice (no leading L axis)."""
    from ..ops import pallas_ops
    fused_attn, fused_mlp = _fused_block_modes(cfg, x, cp_mesh,
                                               cp_axis_level)
    if isinstance(lp.get("wq"), dict) or isinstance(lp.get("w_gate"), dict):
        # int8 quantize_params leaves: the fused-block kernels take dense
        # weight refs, so quantized layers always use the unfused
        # composition (whose matmuls dispatch through _qmm)
        fused_attn = fused_mlp = False
    if fused_attn:
        # norm + qkv + rope + flash + wo + residual in two Pallas kernels
        h = pallas_ops.fused_attention_block(
            x, lp["ln1"], lp["wq"], lp["wk"], lp["wv"], lp["wo"],
            sin, cos, head_dim=cfg.head_dim, eps=cfg.rms_norm_eps)
    else:
        h = x + _attention(cfg, lp,
                           _rms_norm(x, lp["ln1"], cfg.rms_norm_eps),
                           sin, cos, cp_mesh=cp_mesh, cp_axis=cp_axis,
                           cp_axis_level=cp_axis_level)
    if cfg.moe_num_experts > 0:
        mlp_out, aux = _moe_mlp(cfg, lp,
                                _rms_norm(h, lp["ln2"], cfg.rms_norm_eps))
        return h + mlp_out, aux
    if fused_mlp:
        # norm + gate/up + silu + down + residual in one Pallas kernel
        out = pallas_ops.fused_mlp_block(
            h, lp["ln2"], lp["w_gate"], lp["w_up"], lp["w_down"],
            eps=cfg.rms_norm_eps)
        return out, jnp.zeros((), jnp.float32)
    normed = _rms_norm(h, lp["ln2"], cfg.rms_norm_eps)
    return h + _dense_mlp(lp, normed), jnp.zeros((), jnp.float32)


def run_layer_stack(cfg: LlamaConfig, stacked, x, sin, cos,
                    cp_mesh=None, cp_axis="sp", cp_axis_level=False,
                    grad_sync_axis=None):
    """lax.scan over the stacked layer axis (compiler-friendly sequential
    control flow; remat per layer = the recompute strategy).

    grad_sync_axis: when set (manual shard_map data parallelism), each
    layer's parameter slice is routed through ``reduce_in_backward`` so
    the transposed scan emits one gradient all-reduce per layer *inside*
    the backward loop — overlapped with the remaining backward compute —
    instead of a single fused tail collective."""
    layer_fn = functools.partial(decoder_layer, cp_axis_level=cp_axis_level,
                                 cp_mesh=cp_mesh,
                                 cp_axis=cp_axis)

    def body(carry, lp):
        h, aux = carry
        if grad_sync_axis is not None:
            from ..distributed.overlap import reduce_tree_in_backward
            lp = reduce_tree_in_backward(lp, grad_sync_axis)
        fn = layer_fn
        if cfg.use_remat:
            policy = None  # "full": save nothing, recompute the layer
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_saveable
            fn = jax.checkpoint(layer_fn, static_argnums=(0,),
                                policy=policy)
        h, a = fn(cfg, lp, h, sin, cos)
        return (h, aux + a), None
    (x, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), stacked)
    return x, aux


def forward_pure(cfg: LlamaConfig, params, input_ids, sp_axis=None,
                 cp_mesh=None, cp_axis="sp", grad_sync_axis=None):
    """Full forward: ids -> logits (fp32). sp_axis: mesh axis name to shard
    the sequence dimension of activations on (Megatron-style sequence
    parallelism for the elementwise/norm work). cp_mesh: enable ring-
    attention context parallelism over the mesh's 'sp' axis — sequence
    sharded end to end, exact causal attention at O(S/sp) memory."""
    B, S = input_ids.shape
    sin, cos = _rope_tables(cfg, S)
    x = jnp.take(params["embed"], input_ids, axis=0)
    if cp_mesh is not None:
        # pin ONLY the sequence dim: UNCONSTRAINED (not None — None means
        # replicated) leaves batch/hidden placement to GSPMD, so dp batch
        # sharding survives and no 'dp' axis is required of cp meshes
        x = lax.with_sharding_constraint(
            x, P(P.UNCONSTRAINED, cp_axis, P.UNCONSTRAINED))
    elif sp_axis is not None:
        x = lax.with_sharding_constraint(x, P("dp", sp_axis, None))
    x, aux = run_layer_stack(cfg, params["layers"], x, sin, cos,
                             cp_mesh=cp_mesh, cp_axis=cp_axis,
                             grad_sync_axis=grad_sync_axis)
    x = _rms_norm(x, params["norm_f"], cfg.rms_norm_eps)
    logits = _qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, aux


def loss_fn(cfg: LlamaConfig, params, batch, sp_axis=None,
            cp_mesh=None, cp_axis="sp", grad_sync_axis=None):
    ids, labels = batch["input_ids"], batch["labels"]
    logits, aux = forward_pure(cfg, params, ids, sp_axis, cp_mesh=cp_mesh,
                               cp_axis=cp_axis,
                               grad_sync_axis=grad_sync_axis)
    # logsumexp form: ce = lse - target_logit. Avoids materializing the
    # full [B, S, V] log-softmax (1 GB fp32 at bench shapes) — XLA fuses
    # the reduction into the lm_head matmul epilogue.
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    ce = jnp.mean(lse - tgt)
    return ce + 0.01 * aux, ce


# ---------------------------------------------------------------------------
# KV-cache inference (models/decoding.py core)
# ---------------------------------------------------------------------------

def forward_with_cache(cfg: LlamaConfig, params, tokens, cache, pos):
    """Chunked cached forward: process ``tokens`` [B, T] starting at
    sequence offset ``pos`` against per-layer KV caches. For dense
    configs this is the same math as forward_pure (rope at absolute
    positions, GQA-width cache) — cached greedy decode reproduces the
    uncached forward token-for-token (asserted in test_generation).
    MoE configs decode with per-chunk capacity (C computed from the
    chunk's tokens, so single-token steps are effectively dropless); this
    intentionally differs from the training forward, whose GShard
    capacity makes tokens compete across the whole sequence. Serves both
    prefill (T=prompt) and decode (T=1)."""
    from .decoding import KVCache, cached_attention_core

    B, T = tokens.shape
    nh, nkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, \
        cfg.head_dim
    H = cfg.hidden_size
    sin_full, cos_full = _rope_tables(cfg, cfg.max_position_embeddings)
    sin = lax.dynamic_slice_in_dim(sin_full, pos, T, axis=0)
    cos = lax.dynamic_slice_in_dim(cos_full, pos, T, axis=0)
    x = jnp.take(params["embed"], tokens, axis=0)

    def body(h, inp):
        lp, ck, cv = inp
        xn = _rms_norm(h, lp["ln1"], cfg.rms_norm_eps)
        q = _apply_rope(_qmm(xn, lp["wq"]).reshape(B, T, nh, d), sin, cos)
        k = _apply_rope(_qmm(xn, lp["wk"]).reshape(B, T, nkv, d), sin, cos)
        v = _qmm(xn, lp["wv"]).reshape(B, T, nkv, d)
        out, ck, cv = cached_attention_core(q, k, v, ck, cv, pos)
        h = h + _qmm(out.reshape(B, T, H), lp["wo"])
        hn = _rms_norm(h, lp["ln2"], cfg.rms_norm_eps)
        if cfg.moe_num_experts > 0:
            mlp_out, _aux = _moe_mlp(cfg, lp, hn)
            h = h + mlp_out
        else:
            h = h + _dense_mlp(lp, hn)
        return h, (ck, cv)

    x, (new_k, new_v) = lax.scan(body, x,
                                 (params["layers"], cache.k, cache.v))
    x = _rms_norm(x, params["norm_f"], cfg.rms_norm_eps)
    logits = _qmm(x, params["lm_head"]).astype(jnp.float32)
    return logits, KVCache(new_k, new_v)


def forward_paged(cfg: LlamaConfig, params, tokens, k_pages, v_pages,
                  block_tables, seq_lens, q_lens, *,
                  k_scales=None, v_scales=None):
    """Ragged mixed prefill+decode forward over a paged KV cache (the
    serving engine's step function).

    tokens        [R, Tc] int32   current-chunk token slots; request r
                                  uses tokens[r, :q_lens[r]]
    k/v_pages     [L, nkv, P, page, d] per-layer pools
    block_tables  [R, Bmax] i32   pool page of each logical kv block
                                  (page 0 = allocator's reserved null
                                  page, absorbs padding-token scatters)
    seq_lens      [R] i32         total kv length incl. this chunk
    q_lens        [R] i32         chunk lengths (0 = inactive slot)
    k/v_scales    [L, nkv, P] f32 per-page dequant scales — presence
                                  selects the quantized-KV path: pools
                                  hold int8 pages, new k/v are
                                  quantize-on-write requantized per
                                  page, and attention dequants on read

    Fixed shapes throughout — one compilation per (R, Tc, pool)
    signature.  Rope runs at each token's absolute position
    (seq_lens - q_lens + t), new k/v are scattered through the block
    table, and attention is ``ops.pallas_ops.ragged_paged_attention``
    (jnp reference off-TPU).  Returns (logits [R, Tc, V] fp32,
    (k_pages, v_pages)) — with scales, (k_pages, v_pages, k_scales,
    v_scales); logits in padding rows are garbage by contract —
    callers read row q_lens[r] - 1.

    Quantized-KV write path: a per-request window of W logical blocks
    starting at the chunk's first page is gathered, dequantized,
    updated with the chunk's new tokens, re-scaled per page (absmax /
    127) and requantized back.  Window positions at/beyond seq_len are
    zero-masked before the rescale, so a recycled page's previous
    content can never leak into the new owner's page scale — writes
    are a pure function of the request's own tokens, which keeps
    replay after preemption and prefix-cache reuse deterministic.
    Requantization is exact for untouched tokens while the page scale
    is unchanged (dequant of q*s is lossless and the absmax token
    requants to ±127), but a page written under a different chunking
    schedule can differ in the last int8 bit — quantized streams are
    parity-within-tolerance, not bit-identical (docs/serving.md).
    Window slots whose block-table entry is 0 (unallocated → the
    reserved null page) are dropped from the scatter, keeping the
    null page zero."""
    from ..ops.pallas_ops import ragged_paged_attention

    R, Tc = tokens.shape
    nh, nkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, \
        cfg.head_dim
    H = cfg.hidden_size
    rep = nh // nkv
    page = k_pages.shape[3]
    num_pages = k_pages.shape[2]

    # absolute position of each token slot, clipped for the rope gather
    start = (seq_lens - q_lens).astype(jnp.int32)        # [R]
    t_off = jnp.arange(Tc, dtype=jnp.int32)
    qpos = start[:, None] + t_off[None, :]               # [R, Tc]
    valid = t_off[None, :] < q_lens[:, None]             # [R, Tc]
    qpos_c = jnp.clip(qpos, 0, cfg.max_position_embeddings - 1)
    sin_full, cos_full = _rope_tables(cfg, cfg.max_position_embeddings)
    sin = jnp.take(sin_full, qpos_c, axis=0)             # [R, Tc, D]
    cos = jnp.take(cos_full, qpos_c, axis=0)

    def rope(x):
        # per-token tables (ragged positions), else same as _apply_rope
        half = x.shape[-1] // 2
        x1, x2 = x[..., :half], x[..., half:]
        rot = jnp.concatenate([-x2, x1], axis=-1)
        return (x * cos[:, :, None, :].astype(x.dtype)
                + rot * sin[:, :, None, :].astype(x.dtype))

    # flat pool destination of each new token, through the block table;
    # padding tokens land on the null page (never mapped, never read)
    blk = jnp.clip(qpos_c // page, 0, block_tables.shape[1] - 1)
    phys = jnp.take_along_axis(block_tables, blk, axis=1)  # [R, Tc]
    dest = jnp.where(valid, phys * page + qpos_c % page, 0).reshape(-1)

    quant_kv = k_scales is not None
    if quant_kv:
        # R-M-W window per request: W logical blocks from the chunk's
        # first page (covers Tc tokens straddling page boundaries)
        Bmax = block_tables.shape[1]
        W = Tc // page + 2
        first_blk = (jnp.maximum(start, 0) // page).astype(jnp.int32)
        wblk = first_blk[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        wvalid = (wblk < Bmax) & (q_lens > 0)[:, None]         # [R, W]
        phys_w = jnp.take_along_axis(
            block_tables, jnp.clip(wblk, 0, Bmax - 1), axis=1)
        wvalid = wvalid & (phys_w > 0)   # never write the null page
        flat_w = phys_w.reshape(-1)                            # [R*W]
        # OOB sentinel + mode="drop" discards invalid window slots
        scatter_pg = jnp.where(wvalid.reshape(-1), flat_w, num_pages)
        rel = qpos - (first_blk * page)[:, None]               # [R, Tc]
        rel_c = jnp.where(valid, rel, W * page)                # OOB drop
        rows = jnp.broadcast_to(
            jnp.arange(R, dtype=jnp.int32)[:, None], (R, Tc))
        # window positions at/beyond seq_len hold garbage (recycled
        # pages keep their previous owner's bytes); zero them so the
        # page absmax — and therefore every written byte — depends
        # only on this request's own tokens
        wpos = (first_blk * page)[:, None] \
            + jnp.arange(W * page, dtype=jnp.int32)[None, :]   # [R,W*page]
        live = wpos < seq_lens[:, None]

        def quant_write(pool, scales, new_t):
            # pool [nkv, P, page, d] int8 · scales [nkv, P] f32 ·
            # new_t [nkv, R, Tc, d] f32 — gather window, dequant,
            # insert new tokens, per-page absmax rescale, requantize
            win = jnp.take(pool, flat_w, axis=1).astype(jnp.float32)
            sc = jnp.take(scales, flat_w, axis=1)              # [nkv,R*W]
            deq = (win * sc[:, :, None, None]).reshape(
                nkv, R, W * page, d)
            deq = deq.at[:, rows, rel_c].set(new_t, mode="drop")
            deq = jnp.where(live[None, :, :, None], deq, 0.0)
            wp = deq.reshape(nkv, R, W, page, d)
            amax = jnp.max(jnp.abs(wp), axis=(3, 4))           # [nkv,R,W]
            new_sc = jnp.maximum(amax, 1e-8) / 127.0
            qp = jnp.clip(jnp.round(wp / new_sc[..., None, None]),
                          -127, 127).astype(pool.dtype)
            pool = pool.at[:, scatter_pg].set(
                qp.reshape(nkv, R * W, page, d), mode="drop")
            scales = scales.at[:, scatter_pg].set(
                new_sc.reshape(nkv, R * W), mode="drop")
            return pool, scales

    x = jnp.take(params["embed"], tokens, axis=0)

    def body(h, inp):
        if quant_kv:
            lp, kp, vp, ks, vs = inp
        else:
            lp, kp, vp = inp
            ks = vs = None
        xn = _rms_norm(h, lp["ln1"], cfg.rms_norm_eps)
        q = rope(_qmm(xn, lp["wq"]).reshape(R, Tc, nh, d))
        k = rope(_qmm(xn, lp["wk"]).reshape(R, Tc, nkv, d))
        v = _qmm(xn, lp["wv"]).reshape(R, Tc, nkv, d)
        if quant_kv:
            kp, ks = quant_write(
                kp, ks, k.transpose(2, 0, 1, 3).astype(jnp.float32))
            vp, vs = quant_write(
                vp, vs, v.transpose(2, 0, 1, 3).astype(jnp.float32))
        else:
            # scatter new k/v: [R, Tc, nkv, d] -> [nkv, R*Tc, d] at dest
            k_t = k.transpose(2, 0, 1, 3).reshape(nkv, R * Tc, d)
            v_t = v.transpose(2, 0, 1, 3).reshape(nkv, R * Tc, d)
            kp = kp.reshape(nkv, num_pages * page, d).at[:, dest].set(
                k_t.astype(kp.dtype)).reshape(nkv, num_pages, page, d)
            vp = vp.reshape(nkv, num_pages * page, d).at[:, dest].set(
                v_t.astype(vp.dtype)).reshape(nkv, num_pages, page, d)
        # kernel layout [R, nkv, Tc*rep, d]: row t*rep + j = q head
        # k*rep + j of token t (the h // rep GQA mapping)
        qk = q.reshape(R, Tc, nkv, rep, d).transpose(
            0, 2, 1, 3, 4).reshape(R, nkv, Tc * rep, d)
        out = ragged_paged_attention(qk, kp, vp, block_tables,
                                     seq_lens, q_lens, rep=rep,
                                     k_scales=ks, v_scales=vs)
        out = out.reshape(R, nkv, Tc, rep, d).transpose(
            0, 2, 1, 3, 4).reshape(R, Tc, H)
        h = h + _qmm(out.astype(h.dtype), lp["wo"])
        hn = _rms_norm(h, lp["ln2"], cfg.rms_norm_eps)
        if cfg.moe_num_experts > 0:
            mlp_out, _aux = _moe_mlp(cfg, lp, hn)
            h = h + mlp_out
        else:
            h = h + _dense_mlp(lp, hn)
        if quant_kv:
            return h, (kp, vp, ks, vs)
        return h, (kp, vp)

    if quant_kv:
        x, (new_k, new_v, new_ks, new_vs) = lax.scan(
            body, x, (params["layers"], k_pages, v_pages,
                      k_scales, v_scales))
    else:
        x, (new_k, new_v) = lax.scan(body, x,
                                     (params["layers"], k_pages, v_pages))
    x = _rms_norm(x, params["norm_f"], cfg.rms_norm_eps)
    logits = _qmm(x, params["lm_head"]).astype(jnp.float32)
    if quant_kv:
        return logits, (new_k, new_v, new_ks, new_vs)
    return logits, (new_k, new_v)


def _cfg_key(cfg):
    return tuple(sorted((k, str(v))
                        for k, v in dataclasses.asdict(cfg).items()))


def generate(cfg: LlamaConfig, params, input_ids, max_new_tokens,
             temperature=0.0, top_k=0, rng=None, eos_token_id=None):
    """[B, P] prompt -> [B, max_new_tokens] continuations, whole decode
    loop on device (one compiled scan, memoized per signature)."""
    from .decoding import model_generate

    return model_generate(
        functools.partial(forward_with_cache, cfg),
        num_layers=cfg.num_hidden_layers,
        kv_heads=cfg.num_key_value_heads, head_dim=cfg.head_dim,
        max_positions=cfg.max_position_embeddings, cache_dtype=cfg.dtype,
        cache_key=("llama", _cfg_key(cfg)), params=params,
        input_ids=input_ids, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, rng=rng,
        eos_token_id=eos_token_id)


# ---------------------------------------------------------------------------
# parallel train step
# ---------------------------------------------------------------------------

def build_train_step(cfg: LlamaConfig, topo, optimizer=None, use_pp=None,
                     n_microbatches=None, zero=True, schedule="gpipe",
                     virtual_pp=None, overlap=False):
    """Compiled full training step over the hybrid mesh.

    Returns (step_fn, init_fn):
      init_fn(rng) -> (params, opt_state) placed per param_specs (+ZeRO
      opt-state sharding over 'dp').
      step_fn(params, opt_state, batch) -> (params, opt_state, metrics).

    use_pp: pipeline over the 'pp' axis with shard_map; defaults to
    pp_degree > 1. schedule: "gpipe" (autodiff-transposed scan) or "1f1b"
    (hand-scheduled forward/backward interleave, O(pp) activation
    residency — reference pipeline_parallel.py:228).

    overlap: enable compute/communication overlap. With schedule='1f1b'
    the pipeline issues stage-boundary ppermutes one tick ahead of the
    consuming compute (double-buffered edge activations). On a pure-DP
    topology the gradient all-reduce is split into per-layer psums
    emitted inside the backward scan (``reduce_in_backward``) plus
    bucketed collectives for the tail params, instead of one fused tail
    all-reduce. Other topologies ignore the flag.
    """
    import optax
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}; expected 'gpipe', "
            "'1f1b' or 'interleaved'")
    if virtual_pp is not None and schedule != "interleaved":
        raise ValueError(
            "virtual_pp only applies to schedule='interleaved'")
    mesh = topo.mesh
    pp = topo.pp_degree
    use_pp = (pp > 1) if use_pp is None else use_pp
    cp_in_pp = use_pp and getattr(topo, "sp_degree", 1) > 1
    if cp_in_pp and schedule != "gpipe":
        raise ValueError(
            "context parallelism (sp > 1) composes with pipeline "
            "parallelism on the GPipe schedule only (ring attention "
            "inside the pp x sp shard_map); use schedule='gpipe' or "
            "drop one axis")
    opt = optimizer or optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    specs = param_specs(cfg)

    grad_fn = None
    if use_pp and schedule == "1f1b":
        from ..distributed.pipeline import pipeline_1f1b_value_and_grad

        def grad_fn(params, batch):
            total, ce, grads = pipeline_1f1b_value_and_grad(
                cfg, mesh, n_microbatches or pp, params, batch,
                overlap=overlap)
            return (total, ce), grads
    elif use_pp and schedule == "interleaved":
        from ..distributed.pipeline import pipeline_interleaved_loss_fn
        # virtual stages per device: as many 2-chunk splits as the layer
        # count allows (the reference's virtual_pp_degree)
        v = virtual_pp or (2 if cfg.num_hidden_layers % (pp * 2) == 0
                           else 1)
        loss = functools.partial(pipeline_interleaved_loss_fn, cfg, mesh,
                                 n_microbatches or pp, v)
    elif use_pp:
        from ..distributed.pipeline import pipeline_loss_fn
        loss = functools.partial(pipeline_loss_fn, cfg, mesh,
                                 n_microbatches or pp,
                                 cp_axis="sp" if cp_in_pp else None)
    else:
        cp_mesh = mesh if getattr(topo, "sp_degree", 1) > 1 else None
        dp_deg = topo.dims.get("dp", 1)
        # pure-DP overlap: manual shard_map over 'dp' with per-layer
        # backward-scan gradient psums + bucketed tail collectives. Only
        # sound when no other axis carries model state (params fully
        # replicated across 'dp').
        overlap_dp = (overlap and cp_mesh is None and dp_deg > 1
                      and topo.dims.get("mp", 1) == 1
                      and topo.dims.get("sharding", 1) == 1
                      and cfg.moe_num_experts == 0)
        if overlap_dp:
            from ..distributed.overlap import bucketed_psum

            def _dp_body(params, batch):
                def local_loss(p):
                    # local mean loss scaled by 1/dp: psum of its grads
                    # over 'dp' is exactly the global-batch gradient
                    t, c = loss_fn(cfg, p, batch, grad_sync_axis="dp")
                    return t / dp_deg, (t, c)
                (_, (t, c)), grads = jax.value_and_grad(
                    local_loss, has_aux=True)(params)
                # layer grads were psum'd per layer inside the backward
                # scan; the non-stacked tail reduces in byte-bounded
                # buckets so early buckets overlap late backward compute
                tail = bucketed_psum(
                    {k: v for k, v in grads.items() if k != "layers"},
                    "dp")
                grads = dict(grads, **tail)
                return lax.pmean(t, "dp"), lax.pmean(c, "dp"), grads

            def grad_fn(params, batch):
                param_p = jax.tree_util.tree_map(lambda _: P(), params)
                total, ce, grads = jax.shard_map(
                    _dp_body, mesh=mesh,
                    in_specs=(param_p,
                              {"input_ids": P("dp", None),
                               "labels": P("dp", None)}),
                    out_specs=(P(), P(), param_p),
                    axis_names={"dp"}, check_vma=False)(params, batch)
                return (total, ce), grads
        else:
            def loss(params, batch):
                if cp_mesh is not None:  # ring-attention context parallel
                    return loss_fn(cfg, params, batch, cp_mesh=cp_mesh)
                return loss_fn(cfg, params, batch, sp_axis="mp")

    from ._sharding_utils import sharding_tree
    param_sh = sharding_tree(mesh, specs)

    # ZeRO axis: the dedicated 'sharding' axis when the topology carves
    # one out (fleet's 4-D ["data","pipe","sharding","model"]), else the
    # data axis itself (pure-DP ZeRO)
    zero_axis = "sharding" if topo.dims.get("sharding", 1) > 1 else "dp"
    zero_degree = topo.dims.get(zero_axis, 1)

    def zero_shard_spec(spec, shape):
        # ZeRO-1: shard the largest unsharded dim of each optimizer-state
        # array over the zero axis when divisible
        dims = list(spec) + [None] * (len(shape) - len(spec))
        if not zero or zero_axis in dims or not shape:
            return P(*dims) if dims else P()
        n = zero_degree
        for i, d in sorted(enumerate(shape), key=lambda t: -t[1]):
            if dims[i] is None and d % n == 0 and d >= n:
                dims[i] = zero_axis
                break
        return P(*dims)

    # map each opt-state leaf to the spec of its matching param by
    # pytree path: optax states (mu/nu/trace/...) mirror the param
    # tree under a state-field prefix, so the param's path is a
    # suffix of the state leaf's path. Shape-keyed matching would
    # collide for same-shape params (wq/wo both (L,H,H)) and hand
    # Adam moments the wrong placement.
    flat_specs, _ = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda s: isinstance(s, P))
    spec_by_path = [(jax.tree_util.keystr(path), s)
                    for path, s in flat_specs]

    def init_fn(rng):
        with mesh:
            params = jax.jit(
                lambda k: init_params(cfg, k),
                out_shardings=param_sh)(rng)
            opt_state = jax.jit(
                opt.init,
                out_shardings=None)(params)
            # re-place opt state with ZeRO sharding
            def place(x, pspec):
                if not hasattr(x, "shape") or x.ndim == 0:
                    return x  # scalars: replicate_scalars below
                return jax.device_put(
                    x, NamedSharding(mesh, zero_shard_spec(
                        pspec, x.shape)))

            def place_leaf(path, x):
                key = jax.tree_util.keystr(path)
                pspec = next((s for pk, s in spec_by_path
                              if key.endswith(pk)), P())
                return place(x, pspec)

            opt_state = jax.tree_util.tree_map_with_path(
                place_leaf, opt_state)
            from ._sharding_utils import replicate_scalars
            opt_state = replicate_scalars(mesh, opt_state)
        return params, opt_state

    def step(params, opt_state, batch):
        if grad_fn is not None:
            (total, ce), grads = grad_fn(params, batch)
        else:
            (total, ce), grads = jax.value_and_grad(
                lambda p: loss(p, batch), has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": total, "ce": ce}

    batch_axes = getattr(topo, "batch_axes", "dp")
    batch_sh = {"input_ids": NamedSharding(mesh, P(batch_axes, None)),
                "labels": NamedSharding(mesh, P(batch_axes, None))}
    step_jit = jax.jit(step, in_shardings=(param_sh, None, batch_sh),
                       out_shardings=(param_sh, None, None),
                       donate_argnums=(0, 1))

    def step_fn(params, opt_state, batch):
        with mesh:
            return step_jit(params, opt_state, batch)

    def abstract_state():
        """ShapeDtypeStructs (with shardings) for (params, opt_state) —
        lets tools (pod_report, bench) lower/compile the step and read
        its memory_analysis() without ever materializing the weights."""
        p_abs = jax.eval_shape(functools.partial(init_params, cfg),
                               jax.ShapeDtypeStruct((2,), jnp.uint32))
        p_abs = jax.tree_util.tree_map(
            lambda l, sh: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                               sharding=sh),
            p_abs, param_sh)
        o_abs = jax.eval_shape(opt.init, p_abs)

        def leaf_abs(path, x):
            shape = tuple(getattr(x, "shape", ()) or ())
            if not shape:
                sh = NamedSharding(mesh, P())
            else:
                key = jax.tree_util.keystr(path)
                pspec = next((s for pk, s in spec_by_path
                              if key.endswith(pk)), P())
                sh = NamedSharding(mesh, zero_shard_spec(pspec, shape))
            return jax.ShapeDtypeStruct(shape, x.dtype, sharding=sh)

        o_abs = jax.tree_util.tree_map_with_path(leaf_abs, o_abs)
        return p_abs, o_abs

    step_fn.jitted = step_jit
    step_fn.abstract_state = abstract_state
    step_fn.batch_shardings = batch_sh
    return step_fn, init_fn


# ---------------------------------------------------------------------------
# eager Layer face
# ---------------------------------------------------------------------------

from ..nn.layer.layers import Layer, Parameter  # noqa: E402
from ..core.tensor import Tensor, apply_op  # noqa: E402


class LlamaForCausalLM(GenerationMixin, Layer):
    """Eager/dygraph face over the functional core: parameters are the same
    stacked pytree exposed as Layer parameters, so state_dict naming is
    stable and the eager forward matches forward_pure bit-for-bit."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        key = jax.random.PRNGKey(0)
        raw = init_params(config, key)
        self._flat = {}
        for name, arr in _flatten_params(raw):
            p = Parameter(arr)
            p.name = name
            self.add_parameter(name.replace(".", "_"), p)
            self._flat[name] = p

    def _tree(self):
        raw = {}
        for name, p in self._flat.items():
            raw[name] = p._array
        return _unflatten_params(raw)

    def forward(self, input_ids, labels=None):
        cfg = self.config
        flat_names = list(self._flat)
        tensors = [self._flat[n] for n in flat_names]

        def _f(ids, *arrs):
            raw = dict(zip(flat_names, arrs))
            params = _unflatten_params(raw)
            logits, aux = forward_pure(cfg, params, ids)
            return logits
        ids_t = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(np.asarray(input_ids)))
        logits = apply_op(_f, ids_t, *tensors, op_name="llama_forward")
        return self._maybe_loss(logits, labels)

    def _maybe_loss(self, logits, labels):
        if labels is not None:
            from ..nn import functional as F
            from ..tensor.manipulation import reshape
            V = logits.shape[-1]
            loss = F.cross_entropy(reshape(logits, [-1, V]),
                                   reshape(labels, [-1]))
            return loss, logits
        return logits


def _flatten_params(tree, prefix=""):
    out = []
    for k, v in tree.items():
        name = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.extend(_flatten_params(v, name))
        else:
            out.append((name, v))
    return out


def _unflatten_params(flat):
    tree = {}
    for name, v in flat.items():
        parts = name.split(".")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


LlamaForCausalLM._generate_fn = staticmethod(generate)
