"""GPT-2-style decoder family: pre-LN blocks, learned positional
embeddings, fused-QKV projection, GELU MLP, tied LM head.

Reference analog: the GPT nets PaddleNLP trains on the fleet stack (the
reference repo itself ships the fused kernels they ride:
paddle/fluid/operators/fused/fused_multi_transformer_op.cu,
fused_feedforward); architecture follows Radford et al. 2019. Same
functional design as models/llama.py: stacked [L, ...] parameter pytree,
lax.scan over layers, Pallas flash attention when shapes qualify, and
KV-cache generation through models/decoding.py.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer, Parameter
from .decoding import GenerationMixin

__all__ = ["GPTConfig", "init_params", "forward_pure", "loss_fn",
           "forward_with_cache", "generate", "GPTForCausalLM"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0          # 0 -> 4 * hidden
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    use_remat: bool = False
    remat_policy: str = "dots"

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_attention_heads == 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def init_params(cfg: GPTConfig, key) -> Dict[str, Any]:
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    ks = iter(jax.random.split(key, 8))
    std = 0.02

    def init(k_, shape, scale=1.0):
        return (jax.random.normal(k_, shape, jnp.float32)
                * std * scale).astype(cfg.dtype)

    # residual-path projections scaled by 1/sqrt(2L) (GPT-2 init)
    res = 1.0 / (2 * L) ** 0.5
    return {
        "wte": init(next(ks), (cfg.vocab_size, H)),
        "wpe": init(next(ks), (cfg.max_position_embeddings, H)),
        "layers": {
            "ln1_g": jnp.ones((L, H), cfg.dtype),
            "ln1_b": jnp.zeros((L, H), cfg.dtype),
            "attn_w": init(next(ks), (L, H, 3 * H)),
            "attn_b": jnp.zeros((L, 3 * H), cfg.dtype),
            "proj_w": init(next(ks), (L, H, H), res),
            "proj_b": jnp.zeros((L, H), cfg.dtype),
            "ln2_g": jnp.ones((L, H), cfg.dtype),
            "ln2_b": jnp.zeros((L, H), cfg.dtype),
            "fc_w": init(next(ks), (L, H, I)),
            "fc_b": jnp.zeros((L, I), cfg.dtype),
            "fcp_w": init(next(ks), (L, I, H), res),
            "fcp_b": jnp.zeros((L, H), cfg.dtype),
        },
        "lnf_g": jnp.ones((H,), cfg.dtype),
        "lnf_b": jnp.zeros((H,), cfg.dtype),
    }


def _ln(x, g, b, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * g + b


def _qkv(cfg: GPTConfig, lp, xn):
    B, T, H = xn.shape
    nh, d = cfg.num_attention_heads, cfg.head_dim
    qkv = xn @ lp["attn_w"] + lp["attn_b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return (q.reshape(B, T, nh, d), k.reshape(B, T, nh, d),
            v.reshape(B, T, nh, d))


def _block(cfg: GPTConfig, lp, x):
    eps = cfg.layer_norm_epsilon
    B, T, H = x.shape
    q, k, v = _qkv(cfg, lp, _ln(x, lp["ln1_g"], lp["ln1_b"], eps))
    from ..ops import pallas_ops
    att = pallas_ops.causal_attention(q, k, v).reshape(B, T, H)
    x = x + att @ lp["proj_w"] + lp["proj_b"]
    hn = _ln(x, lp["ln2_g"], lp["ln2_b"], eps)
    mlp = jax.nn.gelu(hn @ lp["fc_w"] + lp["fc_b"]) @ lp["fcp_w"] \
        + lp["fcp_b"]
    return x + mlp


def forward_pure(cfg: GPTConfig, params, input_ids):
    """ids [B, S] -> logits [B, S, V] fp32 (LM head tied to wte)."""
    B, S = input_ids.shape
    pos = jnp.arange(S)
    x = jnp.take(params["wte"], input_ids, axis=0) \
        + jnp.take(params["wpe"], pos, axis=0)[None]

    def body(h, lp):
        fn = _block
        if cfg.use_remat:
            policy = jax.checkpoint_policies.dots_saveable \
                if cfg.remat_policy == "dots" else None
            fn = jax.checkpoint(_block, static_argnums=(0,), policy=policy)
        return fn(cfg, lp, h), None

    x, _ = lax.scan(body, x, params["layers"])
    x = _ln(x, params["lnf_g"], params["lnf_b"], cfg.layer_norm_epsilon)
    return (x @ params["wte"].T).astype(jnp.float32)


def loss_fn(cfg: GPTConfig, params, batch):
    ids, labels = batch["input_ids"], batch["labels"]
    logits = forward_pure(cfg, params, ids)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt)


# -- KV-cache inference ------------------------------------------------------

def forward_with_cache(cfg: GPTConfig, params, tokens, cache, pos):
    from .decoding import KVCache, cached_attention_core

    B, T = tokens.shape
    H = cfg.hidden_size
    eps = cfg.layer_norm_epsilon
    positions = pos + jnp.arange(T)
    x = jnp.take(params["wte"], tokens, axis=0) \
        + jnp.take(params["wpe"], positions, axis=0)[None]

    def body(h, inp):
        lp, ck, cv = inp
        q, k, v = _qkv(cfg, lp, _ln(h, lp["ln1_g"], lp["ln1_b"], eps))
        out, ck, cv = cached_attention_core(q, k, v, ck, cv, pos)
        h = h + out.reshape(B, T, H) @ lp["proj_w"] + lp["proj_b"]
        hn = _ln(h, lp["ln2_g"], lp["ln2_b"], eps)
        h = h + jax.nn.gelu(hn @ lp["fc_w"] + lp["fc_b"]) @ lp["fcp_w"] \
            + lp["fcp_b"]
        return h, (ck, cv)

    x, (nk, nv) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x = _ln(x, params["lnf_g"], params["lnf_b"], eps)
    return (x @ params["wte"].T).astype(jnp.float32), KVCache(nk, nv)


def generate(cfg: GPTConfig, params, input_ids, max_new_tokens,
             temperature=0.0, top_k=0, rng=None, eos_token_id=None):
    from .decoding import model_generate
    from .llama import _cfg_key

    return model_generate(
        functools.partial(forward_with_cache, cfg),
        num_layers=cfg.num_hidden_layers,
        kv_heads=cfg.num_attention_heads, head_dim=cfg.head_dim,
        max_positions=cfg.max_position_embeddings, cache_dtype=cfg.dtype,
        cache_key=("gpt", _cfg_key(cfg)), params=params,
        input_ids=input_ids, max_new_tokens=max_new_tokens,
        temperature=temperature, top_k=top_k, rng=rng,
        eos_token_id=eos_token_id)


# -- Layer facade ------------------------------------------------------------

class GPTForCausalLM(GenerationMixin, Layer):
    """Eager face over the functional core (same pattern as
    LlamaForCausalLM: parameters are the stacked pytree)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        from .llama import _flatten_params, _unflatten_params
        self._unflatten = _unflatten_params
        raw = init_params(config, jax.random.PRNGKey(0))
        self._flat = {}
        for name, arr in _flatten_params(raw):
            p = Parameter(arr)
            p.name = name
            self.add_parameter(name.replace(".", "_"), p)
            self._flat[name] = p

    def _tree(self):
        return self._unflatten({n: p._array
                                for n, p in self._flat.items()})

    def forward(self, input_ids, labels=None):
        cfg = self.config
        names = list(self._flat)
        tensors = [self._flat[n] for n in names]

        def _f(ids, *arrs):
            params = self._unflatten(dict(zip(names, arrs)))
            return forward_pure(cfg, params, ids)

        ids_t = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(np.asarray(input_ids)))
        logits = apply_op(_f, ids_t, *tensors, op_name="gpt_forward")
        if labels is not None:
            from ..nn import functional as F
            from ..tensor.manipulation import reshape
            V = logits.shape[-1]
            loss = F.cross_entropy(reshape(logits, [-1, V]),
                                   reshape(labels, [-1]))
            return loss, logits
        return logits


GPTForCausalLM._generate_fn = staticmethod(generate)
