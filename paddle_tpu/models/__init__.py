"""Flagship model families (functional cores + Layer facades).

- llama: RoPE/GQA/SwiGLU decoder with 4-D parallel train step (the
  Llama-2 pretrain north star), optional MoE layers, ring-attention CP.
- gpt: GPT-2-style decoder (learned positions, fused QKV, GELU, tied head).
- ernie: encoder pretraining family (MLM+NSP).
- decoding: shared KV-cache autoregressive generation.
"""
from . import llama  # noqa: F401
from . import gpt  # noqa: F401
from . import ernie  # noqa: F401
from . import decoding  # noqa: F401
from . import convert  # noqa: F401

__all__ = ["llama", "gpt", "ernie", "decoding", "convert"]
