"""Checkpoint name compatibility: PaddleNLP/HF llama state_dicts <-> the
stacked parameter pytree.

Reference analog: the state_dict naming contract that lets
paddle.save/load checkpoints flow between PaddleNLP trainers
(python/paddle/framework/io.py pickled nested state_dicts keyed by
parameter name). The TPU build stacks per-layer weights along a leading
L axis for lax.scan/GSPMD, so loading an external checkpoint means
de-interleaving "layers.{i}.<leaf>" names into stacked arrays — this
module is that bridge, in both directions.

Name schema (PaddleNLP LlamaForCausalLM, also HF transformers modulo the
"llama."/"model." prefix):
  {p}.embed_tokens.weight                         -> embed
  {p}.layers.{i}.input_layernorm.weight           -> layers.ln1[i]
  {p}.layers.{i}.self_attn.{q,k,v,o}_proj.weight  -> layers.w{q,k,v,o}[i]
  {p}.layers.{i}.post_attention_layernorm.weight  -> layers.ln2[i]
  {p}.layers.{i}.mlp.{gate,up,down}_proj.weight   -> layers.w_{gate,up,down}[i]
  {p}.norm.weight                                 -> norm_f
  lm_head.weight                                  -> lm_head

Orientation: paddle Linear weights are [in, out] — the same layout the
stacked pytree multiplies with (x @ w) — so PaddleNLP sources load
without transposition; HF/torch Linear stores [out, in], so
``source="hf"`` transposes the projection matrices.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

__all__ = ["llama_from_external_state_dict", "llama_to_external_state_dict"]

_LEAF_MAP = {
    "input_layernorm.weight": "ln1",
    "self_attn.q_proj.weight": "wq",
    "self_attn.k_proj.weight": "wk",
    "self_attn.v_proj.weight": "wv",
    "self_attn.o_proj.weight": "wo",
    "post_attention_layernorm.weight": "ln2",
    "mlp.gate_proj.weight": "w_gate",
    "mlp.up_proj.weight": "w_up",
    "mlp.down_proj.weight": "w_down",
}
_MATRIX_LEAVES = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}
_PREFIXES = ("llama.", "model.", "")


def _to_np(v):
    if hasattr(v, "_array"):  # Tensor facade
        v = v._array
    if hasattr(v, "numpy"):
        try:
            v = v.numpy()
        except (TypeError, ValueError, RuntimeError):
            # torch-style tensors raise RuntimeError until .detach();
            # np.asarray below is the fallback for anything array-like
            pass
    return np.asarray(v)


def _strip_prefix(name: str) -> str:
    for p in ("llama.", "model."):
        if name.startswith(p):
            return name[len(p):]
    return name


def llama_from_external_state_dict(cfg, state_dict: Dict[str, Any],
                                   source: str = "paddlenlp",
                                   strict: bool = True):
    """Per-layer external names -> the stacked pytree init_params builds.
    ``source``: "paddlenlp" (weights [in, out], used as-is) or "hf"
    (torch [out, in]; projections transposed). With ``strict``, missing
    or unknown keys raise with the full lists."""
    if source not in ("paddlenlp", "hf"):
        raise ValueError(f"unknown source {source!r}")
    transpose = source == "hf"
    L = cfg.num_hidden_layers
    sd = {_strip_prefix(k): v for k, v in state_dict.items()}

    per_layer = {leaf: [None] * L for leaf in _LEAF_MAP.values()}
    top = {}
    unknown = []
    for name, v in sd.items():
        arr = _to_np(v)
        if name == "embed_tokens.weight":
            top["embed"] = arr
        elif name == "norm.weight":
            top["norm_f"] = arr
        elif name == "lm_head.weight":
            # lm_head multiplies [H] -> [V]: paddle stores [H, V]; hf [V, H]
            top["lm_head"] = arr.T if transpose else arr
        elif name.startswith("layers."):
            _, idx, leaf = name.split(".", 2)
            i = int(idx)
            mapped = _LEAF_MAP.get(leaf)
            if mapped is None or i >= L:
                unknown.append(name)
                continue
            if transpose and mapped in _MATRIX_LEAVES:
                arr = arr.T
            per_layer[mapped][i] = arr
        else:
            unknown.append(name)

    missing = [k for k in ("embed", "norm_f", "lm_head") if k not in top]
    for leaf, slots in per_layer.items():
        missing += [f"layers.{i}.{leaf}" for i, s in enumerate(slots)
                    if s is None]
    if strict and (missing or unknown):
        raise KeyError(
            f"state_dict mismatch: missing={missing[:8]}"
            f"{'...' if len(missing) > 8 else ''} unknown={unknown[:8]}")

    dtype = cfg.dtype
    layers = {leaf: jnp.asarray(np.stack(slots), dtype)
              for leaf, slots in per_layer.items()
              if all(s is not None for s in slots)}
    return {
        "embed": jnp.asarray(top["embed"], dtype),
        "layers": layers,
        "norm_f": jnp.asarray(top["norm_f"], dtype),
        "lm_head": jnp.asarray(top["lm_head"], dtype),
    }


def llama_to_external_state_dict(cfg, params, prefix: str = "llama.",
                                 source: str = "paddlenlp"):
    """Stacked pytree -> per-layer external names (the inverse bridge, so
    checkpoints trained here load into PaddleNLP/HF trainers)."""
    transpose = source == "hf"
    out = {
        f"{prefix}embed_tokens.weight": np.asarray(params["embed"]),
        f"{prefix}norm.weight": np.asarray(params["norm_f"]),
        "lm_head.weight": (np.asarray(params["lm_head"]).T if transpose
                           else np.asarray(params["lm_head"])),
    }
    inv = {v: k for k, v in _LEAF_MAP.items()}
    for leaf, ext in inv.items():
        stacked = np.asarray(params["layers"][leaf])
        for i in range(stacked.shape[0]):
            arr = stacked[i]
            if transpose and leaf in _MATRIX_LEAVES:
                arr = arr.T
            out[f"{prefix}layers.{i}.{ext}"] = arr
    return out
