"""paddle.onnx surface (scope-gated).

Reference analog: python/paddle/onnx/export.py — a thin wrapper over the
external paddle2onnx converter. This environment ships no onnx package or
runtime, and the TPU serving stack's supported interchange format is the
StableHLO artifact jit.save produces (loadable by the python Predictor and
the native C serving ABI — see paddle_tpu/inference). export() therefore
converts the layer to the supported artifact when asked, and refuses with
a precise error rather than silently writing a file that is not ONNX.
"""
from __future__ import annotations

__all__ = ["export", "is_supported"]


def is_supported() -> bool:
    """True when a real ONNX converter/runtime is importable."""
    try:
        import onnx  # noqa: F401
        return True
    except ImportError:
        return False


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Reference signature (python/paddle/onnx/export.py). Without an
    onnx package this raises and points at jit.save, the supported
    artifact; with one present, conversion would ride paddle2onnx's
    approach (graph export -> onnx opset mapping), which is out of scope
    in this tree."""
    if not is_supported():
        raise NotImplementedError(
            "ONNX export is out of scope on this stack: no onnx package "
            "in the environment. The supported interchange artifact is "
            "StableHLO — use paddle_tpu.jit.save(layer, path, "
            "input_spec=...) and serve it with paddle_tpu.inference "
            "(python) or libpaddle_tpu_capi.so (C ABI).")
    raise NotImplementedError(
        "onnx package found, but the paddle2onnx-style converter is not "
        "bundled in this tree; export via jit.save (StableHLO) instead.")
