"""paddle.save / paddle.load.

Reference analog: python/paddle/framework/io.py:637/:879 — pickled nested
state_dicts with tensor payloads. Format here: pickle with Tensors converted
to numpy (+ dtype tag), so checkpoints are host-portable; orbax-backed
sharded checkpointing for distributed arrays lives in
distributed.checkpoint.
"""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..testing.chaos import chaos_point

__all__ = ["save", "load"]

_BF16_TAG = "__bf16__"          # legacy: float32-upcast payload
_BF16_BITS_TAG = "__bf16_bits__"  # raw uint16 bit payload (half size)


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._array)
        if obj._array.dtype == jnp.bfloat16:
            # raw 16-bit payload: exact, picklable without ml_dtypes,
            # and half the bytes of the legacy float32 upcast. A NEW tag
            # key, so a pre-bits reader sees an untagged dict (loud
            # type/shape failure downstream) instead of silently
            # interpreting bit patterns as float values.
            return {_BF16_BITS_TAG: True, "data": arr.view(np.uint16)}
        return arr
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        # NamedTuple (e.g. optax optimizer states): positional ctor
        return type(obj)(*(_pack(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    if isinstance(obj, dict):
        if obj.get(_BF16_BITS_TAG):
            return Tensor(jnp.asarray(obj["data"]).view(jnp.bfloat16))
        if obj.get(_BF16_TAG):  # legacy float32-upcast encoding
            return Tensor(jnp.asarray(obj["data"]).astype(jnp.bfloat16))
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        return type(obj)(*(_unpack(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Crash-consistent: pickle into a tmp sibling, flush+fsync, then
    atomically ``os.replace`` over ``path`` — a kill at any instant
    leaves either the previous complete file or the new one, never a
    truncated hybrid."""
    if hasattr(path, "write"):
        pickle.dump(_pack(obj), path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.ptq-tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(_pack(obj), f, protocol=protocol)
            f.flush()
            os.fsync(f.fileno())
        chaos_point("io.save.pre_commit", path=path)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # interrupted before the commit rename
            os.remove(tmp)


def load(path, **configs):
    name = getattr(path, "name", None) or repr(path)
    if hasattr(path, "read"):
        try:
            return _unpack(pickle.load(path))
        except (pickle.UnpicklingError, EOFError) as e:
            raise RuntimeError(
                f"checkpoint stream {name} is truncated or corrupt "
                f"({type(e).__name__}: {e})") from e
    try:
        with open(path, "rb") as f:
            return _unpack(pickle.load(f))
    except (pickle.UnpicklingError, EOFError) as e:
        raise RuntimeError(
            f"checkpoint file {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e}); the writing process was likely "
            f"killed mid-save — restore an earlier checkpoint") from e
