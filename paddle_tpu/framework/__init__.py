from .random import seed, get_rng_state, set_rng_state, Generator, \
    default_generator
from .param_attr import ParamAttr
