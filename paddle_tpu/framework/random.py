"""Global RNG state.

Reference analog: python/paddle/framework/random.py (paddle.seed,
get/set_cuda_rng_state) over phi Generator (paddle/phi/core/generator.h).
JAX's RNG is explicitly keyed; this module provides the stateful facade:
a process-global Generator whose key is split per draw. Distributed RNG
parity (mpu/random.py RNGStatesTracker) builds on Generator in
paddle_tpu.distributed.random.
"""
from __future__ import annotations

import threading

import jax
import numpy as np

__all__ = ["seed", "get_rng_state", "set_rng_state", "Generator",
           "default_generator", "next_key"]


class Generator:
    """Splittable stateful RNG over a jax PRNG key."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        # key creation is deferred to first use: PRNGKey executes a jax
        # computation, and the module-level default_generator must not
        # touch a device at import time (e.g. `python -m
        # paddle_tpu.distributed.launch` on a host whose accelerator
        # plugin is unavailable)
        self._key = None
        self._counter = 0
        return self

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            self._counter += 1
            return jax.random.fold_in(self._key, self._counter)

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = state
        self._key = None
        return self

    def initial_seed(self):
        return self._seed


default_generator = Generator(np.random.randint(0, 2**31 - 1))


def seed(value: int):
    """paddle.seed parity — reseeds the global generator."""
    default_generator.manual_seed(int(value))
    return default_generator


def next_key():
    return default_generator.next_key()


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)
