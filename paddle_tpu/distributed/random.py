"""Distributed RNG state tracking.

Reference analog: python/paddle/distributed/fleet/layers/mpu/random.py —
RNGStatesTracker + model_parallel_random_seed: dropout inside TP regions
must use a per-mp-rank seed, while replicated regions share one.
"""
from __future__ import annotations

import contextlib

from ..framework.random import Generator
from .collective import get_rank

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = states

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        from ..framework import random as global_rng
        saved = global_rng.default_generator
        global_rng.default_generator = self.states_[name]
        try:
            yield
        finally:
            global_rng.default_generator = saved


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed or (pyrandom.randint(0, 2 ** 30) + 100)
    global_seed = seed
    local_seed = seed + 1024 + get_rank()
    _TRACKER.reset()
    from ..framework.random import seed as set_global_seed
    set_global_seed(global_seed)
    _TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
