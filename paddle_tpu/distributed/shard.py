"""Sharding utilities: shard_tensor/shard_op markers + parameter placement.

Reference analog: python/paddle/distributed/auto_parallel/interface.py
(shard_tensor:28, shard_op:108) and the Engine's partitioner. On TPU the
"partitioner" is GSPMD: we only annotate; XLA splits.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor, apply_op
from .mesh import get_mesh, ProcessMesh

__all__ = ["shard_tensor", "shard_op", "shard_layer", "with_sharding_constraint",
           "shard_params", "replicate_params"]


def _to_named_sharding(mesh, spec):
    m = mesh.to_jax_mesh() if isinstance(mesh, ProcessMesh) else \
        (mesh or get_mesh())
    return NamedSharding(m, spec if isinstance(spec, PartitionSpec)
                         else PartitionSpec(*spec))


def shard_tensor(x, mesh=None, placements=None, dist_attr=None):
    """Place (or annotate, if traced) a tensor on the mesh."""
    spec = placements if placements is not None else PartitionSpec()
    ns = _to_named_sharding(mesh, spec)
    if isinstance(x._array, jax.core.Tracer):
        def _f(a):
            return jax.lax.with_sharding_constraint(a, ns)
        out = apply_op(_f, x, op_name="shard_tensor")
        return out
    x._set_array(jax.device_put(x._array, ns))
    x.sharding_spec = ns.spec
    return x


def with_sharding_constraint(x, spec, mesh=None):
    ns = _to_named_sharding(mesh, spec)

    def _f(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, ns)
        return jax.device_put(a, ns)
    return apply_op(_f, x, op_name="sharding_constraint")


def shard_op(op_fn, mesh=None, in_specs=None, out_specs=None):
    """Constrain an op's outputs (reference interface.py:108)."""
    def wrapper(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        if out_specs is not None and isinstance(out, Tensor):
            return with_sharding_constraint(out, out_specs, mesh)
        return out
    return wrapper


def shard_layer(layer, process_mesh=None, shard_fn=None,
                input_fn=None, output_fn=None):
    """Apply per-parameter shard_fn (name, param) -> PartitionSpec."""
    for name, p in layer.named_parameters():
        spec = shard_fn(name, p) if shard_fn else PartitionSpec()
        if spec is not None:
            p.sharding_spec = spec
    return layer


def shard_params(layer, mesh=None):
    """Materialize every parameter onto the mesh per its sharding_spec
    annotation (replicated if absent). This is the weight-placement step a
    trainer runs after fleet.init — the Partitioner analog."""
    m = mesh or get_mesh()
    if m is None:
        return layer
    for _, p in layer.named_parameters():
        spec = getattr(p, "sharding_spec", None) or PartitionSpec()
        p._set_array(jax.device_put(p._array, NamedSharding(m, spec)))
    for _, b in layer.named_buffers():
        if b is not None:
            b._set_array(jax.device_put(b._array,
                                        NamedSharding(m, PartitionSpec())))
    return layer


def replicate_params(layer, mesh=None):
    m = mesh or get_mesh()
    if m is None:
        return layer
    ns = NamedSharding(m, PartitionSpec())
    for _, p in layer.named_parameters():
        p._set_array(jax.device_put(p._array, ns))
    return layer
