"""Parallel environment bootstrap + DataParallel.

Reference analog: python/paddle/distributed/parallel.py:318
(init_parallel_env: reads PADDLE_* env from the launcher, TCPStore
rendezvous, ProcessGroup creation) and python/paddle/fluid/dygraph/
parallel.py (DataParallel + EagerReducer grad bucketing).

TPU-native: multi-host bootstrap is jax.distributed.initialize (the
TCPStore/launcher analog); within a host all chips are addressable, so
"one process per device" becomes "one process per host". DataParallel is a
thin wrapper: gradients are averaged by `pmean` inside the compiled step
(GSPMD inserts it from batch sharding), so the EagerReducer's bucketing/
overlap machinery is unnecessary by construction — XLA overlaps the
all-reduce with backward compute during scheduling (SURVEY.md §2.5 item 9).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .mesh import init_mesh, get_topology
from .collective import all_reduce, get_rank, get_world_size

__all__ = ["init_parallel_env", "shutdown", "ParallelEnv", "DataParallel",
           "get_rank", "get_world_size"]

_INITIALIZED = [False]


def shutdown():
    """Tear down the multi-process gang so a worker can exit 0 through
    NORMAL interpreter shutdown — the inverse of init_parallel_env.

    Reference analog: ProcessGroup destruction + tcp_store shutdown at
    trainer exit. The jax coordination service orders the teardown
    internally (its shutdown barrier holds the coordinator open until
    every client has disconnected), so after this returns ``sys.exit(0)``
    is safe; no ``os._exit`` escape hatch is needed. Idempotent, and
    also works when the gang was bootstrapped with raw
    ``jax.distributed.initialize`` instead of init_parallel_env.
    """
    _INITIALIZED[0] = False
    try:
        from jax._src.distributed import global_state as _state
        if getattr(_state, "client", None) is None and \
                getattr(_state, "service", None) is None:
            return  # single-process or already shut down
    except ImportError:  # private path moved: let shutdown() decide
        pass
    jax.distributed.shutdown()


def init_parallel_env(strategy=None):
    """Bootstrap multi-host jax.distributed from PADDLE_*/standard envs."""
    if _INITIALIZED[0]:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER",
                           os.environ.get("MASTER_ADDR"))
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                os.environ.get("WORLD_SIZE", "1")))
    pid = int(os.environ.get("PADDLE_TRAINER_ID",
                             os.environ.get("RANK", "0")))
    if nprocs > 1 and coord:
        port = os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}"
            if ":" not in coord else coord,
            num_processes=nprocs, process_id=pid)
    if get_topology() is None:
        init_mesh()
    _INITIALIZED[0] = True
    return ParallelEnv()


class ParallelEnv:
    """reference: python/paddle/fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def device_id(self):
        return self.local_rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")


class DataParallel(Layer):
    """Wrapper for dygraph DP parity.

    Under the TPU execution model the wrapped forward is unchanged; what
    makes it data-parallel is (a) feeding batch-sharded arrays (see
    distributed.shard_batch / DistributedBatchSampler) and (b) running the
    step under jit with the global mesh, where XLA turns the parameter
    gradients into psums over the 'dp' axis. For eager single-host use with
    explicit multi-device grads, `apply_collective_grads` mirrors the
    reference's fused allreduce hook.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def apply_collective_grads(self):
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, op="avg")

    def scale_loss(self, loss):
        return loss

    @property
    def parameters_(self):
        return self._layers.parameters()
