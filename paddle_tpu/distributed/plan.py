"""First-class parallelism plans: one object from planner to compiled step.

A :class:`Plan` names everything needed to run a training step on a pod:
the mesh axis degrees (``dp/pp/sharding/sp/mp``), the pipeline schedule
and microbatch count, whether compute/communication overlap is enabled,
and (optionally) the per-parameter partition specs in the portable JSON
form ``reshard.spec_to_json`` emits.

Three ways in, one way out:

* ``Plan(dp=2, pp=2, schedule="1f1b", overlap=True)`` — by hand.
* ``Plan.from_report(report_or_path)`` — load the winning topology from a
  ``tools/pod_report.py`` report (or from the executable spec its
  ``--plan-out`` flag writes), so planner → compile → run is one path.
* ``Plan.load(path)`` / ``Plan.from_spec(dict)`` — round-trip the spec.

Out: ``plan.train_step(cfg)`` builds the llama training step for the
plan's topology, and the generic ``plan.compile(fn, ...)`` follows the
Titanax selection rule: explicit ``in_shardings`` **and**
``out_shardings`` → compiler-placed ``jax.jit`` (pjit); only one of them
→ error (half-specified placement silently degrades to GSPMD guessing);
``in_specs``/``out_specs`` → per-device ``shard_map`` for map-style
collectives; neither → plain ``jit``.

Every compiled plan can be gated through the SPMD collective-consistency
checker (``verify=True``, default follows ``FLAGS_tpu_lint``): the step
is traced to a jaxpr and the Level-3 rules (divergent collectives,
rank-dependent loops, axis misuse) must come back clean before the first
real execution.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..profiler import exporter as _exporter
from ..profiler import trace as _trace

__all__ = ["Plan", "PlanError", "PlanCompilationError",
           "PlanVerificationError", "SCHEDULES"]

SCHEDULES = ("none", "gpipe", "1f1b", "interleaved")

AXES = ("dp", "pp", "sharding", "sp", "mp")


class PlanError(Exception):
    """Base for plan construction/compilation/verification failures."""


class PlanCompilationError(PlanError):
    """The compile request is inconsistent (e.g. half-specified
    shardings, or both shardings and specs)."""


class PlanVerificationError(PlanError):
    """The SPMD checker found error-severity findings in the compiled
    step's jaxpr."""

    def __init__(self, findings):
        self.findings = list(findings)
        lines = "; ".join(f"{f.rule}: {f.message}" for f in self.findings)
        super().__init__(
            f"SPMD verification failed with {len(self.findings)} "
            f"error finding(s): {lines}")


def _as_sharding_tree(tree, mesh):
    """Bind a pytree of PartitionSpecs (or already-built Shardings) to
    ``mesh``. Leaves that are PartitionSpecs become NamedShardings; JSON
    spec lists are rebound with missing axes dropped (→ replicated)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def bind(leaf):
        if leaf is None or isinstance(leaf, NamedSharding):
            return leaf
        if isinstance(leaf, P):
            return NamedSharding(mesh, leaf)
        if isinstance(leaf, (list, tuple)):  # reshard JSON form
            from .reshard import _rebind_spec, spec_from_json
            return NamedSharding(
                mesh, spec_from_json(_rebind_spec(list(leaf), mesh)))
        return leaf

    return jax.tree_util.tree_map(
        bind, tree,
        is_leaf=lambda l: l is None or isinstance(l, (P, list, tuple)))


def _error_findings(findings):
    return [f for f in findings if getattr(f, "severity", "") == "error"]


def _put_global(arr, sharding):
    """Place one host array under ``sharding`` — single- OR multi-process
    safe. ``jax.device_put`` can only target addressable devices; in a
    real gang every rank materializes the same deterministic global host
    array and contributes just its addressable shards via
    ``make_array_from_callback`` (the standard multi-controller feeding
    pattern)."""
    import jax
    import numpy as np
    arr = np.asarray(arr)
    # match device_put's dtype canonicalization (int64 -> int32 with x64
    # off); make_array_from_callback feeds raw host bytes to XLA, where
    # a non-canonical dtype corrupts the runtime instead of downcasting
    canon = jax.dtypes.canonicalize_dtype(arr.dtype)
    if arr.dtype != canon:
        arr = arr.astype(canon)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx, _a=arr: _a[idx])


def _wrap_step_tracing(plan: "Plan", step_fn: Callable) -> Callable:
    """Per-rank train-step spans for the flight recorder.

    Each invocation emits a shared-name barrier event (the anchor
    ``trace.merge_ranks`` aligns rank clocks on) and wraps the step in a
    ``train/step`` span; the first traced step of a pipelined plan also
    records the static 1F1B schedule via
    ``trace.record_pipeline_schedule`` so ``tools/trace_report.py`` can
    compute measured overlap with the simulator's exact event schema.
    Tracing off → one dict lookup per step, step_fn runs untouched.
    """
    counter = {"n": 0}

    def traced(params, opt_state, batch):
        if not _trace.enabled():
            return step_fn(params, opt_state, batch)
        n = counter["n"]
        counter["n"] += 1
        if n == 0 and plan.pp > 1 and plan.schedule != "none":
            _trace.record_pipeline_schedule(
                plan.pp, plan.n_microbatches or plan.pp,
                overlap=plan.overlap, step=n)
        _trace.barrier(f"train/step{n}")
        with _trace.span("train/step", step=n, dp=plan.dp, pp=plan.pp,
                         schedule=plan.schedule, overlap=plan.overlap):
            return step_fn(params, opt_state, batch)

    for attr in ("jitted", "abstract_state", "batch_shardings", "plan",
                 "plan_topology"):
        if hasattr(step_fn, attr):
            setattr(traced, attr, getattr(step_fn, attr))
    return traced


@dataclasses.dataclass
class Plan:
    """Executable parallelism plan over the fleet's 5-axis hybrid mesh.

    ``param_specs``, when present, maps '/'-joined parameter paths to
    ``reshard.spec_to_json`` partition specs — the portable form that
    survives meshes with different axis sets (binding to a mesh that
    lacks an axis silently drops it, i.e. replicates that dimension).
    """

    dp: int = 1
    pp: int = 1
    sharding: int = 1
    sp: int = 1
    mp: int = 1
    schedule: str = "none"
    n_microbatches: Optional[int] = None
    overlap: bool = False
    param_specs: Optional[Dict[str, List[Optional[List[str]]]]] = None

    def __post_init__(self):
        if self.schedule not in SCHEDULES:
            raise PlanError(
                f"unknown schedule {self.schedule!r}; expected one of "
                f"{SCHEDULES}")
        for a in AXES:
            d = getattr(self, a)
            if not isinstance(d, int) or d < 1:
                raise PlanError(f"axis degree {a}={d!r} must be a "
                                "positive int")
        if self.schedule != "none" and self.pp == 1:
            raise PlanError(
                f"schedule={self.schedule!r} needs pp > 1 (got pp=1); "
                "use schedule='none' for non-pipelined plans")

    # -- topology -----------------------------------------------------------
    @property
    def world_size(self) -> int:
        return self.dp * self.pp * self.sharding * self.sp * self.mp

    @property
    def dims(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    def topology(self, devices=None):
        """HybridTopology (and its Mesh) for this plan's degrees."""
        import jax
        from .mesh import HybridTopology
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < self.world_size:
            raise PlanError(
                f"plan needs {self.world_size} devices "
                f"({'x'.join(str(d) for d in self.dims.values())}), "
                f"only {len(devices)} available")
        return HybridTopology(dp=self.dp, pp=self.pp,
                              sharding=self.sharding, sp=self.sp,
                              mp=self.mp,
                              devices=devices[:self.world_size])

    # -- generic compile (Titanax selection rule) ---------------------------
    def compile(self, fn: Callable, *, devices=None, mesh=None,
                in_shardings=None, out_shardings=None,
                in_specs=None, out_specs=None, axis_names=None,
                verify: Optional[bool] = None, example_args=None,
                donate_argnums=(), **jit_kwargs):
        """Compile ``fn`` for this plan's mesh.

        Selection rule (SNIPPETS.md Titanax pattern):

        * ``in_shardings`` AND ``out_shardings`` → ``jax.jit`` with
          explicit placements (pjit path — GSPMD inserts collectives).
        * exactly one of them → :class:`PlanCompilationError`. A
          half-specified placement is the silent-degradation case: GSPMD
          would guess the other side and the plan would no longer mean
          what it says.
        * ``in_specs``/``out_specs`` → ``shard_map`` (manual map-style
          collectives: the fn body sees per-device shards and calls
          ``lax.psum``/``ppermute`` itself), wrapped in ``jit``.
        * neither → plain ``jit``.

        Sharding/spec leaves may be PartitionSpecs (bound to the plan
        mesh here) or prebuilt NamedShardings. ``verify`` gates the
        result through the SPMD checker (None → ``FLAGS_tpu_lint``):
        eagerly when ``example_args`` is given, else lazily on the
        first call. The returned callable carries ``.path`` ('pjit' |
        'shard_map' | 'jit'), ``.mesh`` and ``.jitted``.
        """
        import jax

        topo = None
        if mesh is None:
            topo = self.topology(devices)
            mesh = topo.mesh

        have_in_sh = in_shardings is not None
        have_out_sh = out_shardings is not None
        have_specs = (in_specs is not None) or (out_specs is not None)
        if have_in_sh != have_out_sh:
            missing = "out_shardings" if have_in_sh else "in_shardings"
            raise PlanCompilationError(
                "pjit compilation requires BOTH in_shardings and "
                f"out_shardings; {missing} is missing. Half-specified "
                "placements fall back to GSPMD inference and stop "
                "meaning what the plan says — pass both, or use "
                "in_specs/out_specs for the shard_map path")
        if have_in_sh and have_specs:
            raise PlanCompilationError(
                "pass either shardings (pjit path) or specs (shard_map "
                "path), not both")
        if have_specs and ((in_specs is None) != (out_specs is None)):
            raise PlanCompilationError(
                "shard_map compilation requires both in_specs and "
                "out_specs")

        if have_in_sh:
            path = "pjit"
            inner = jax.jit(
                fn,
                in_shardings=_as_sharding_tree(in_shardings, mesh),
                out_shardings=_as_sharding_tree(out_shardings, mesh),
                donate_argnums=donate_argnums, **jit_kwargs)
            traceable = fn
        elif have_specs:
            path = "shard_map"
            names = (set(axis_names) if axis_names is not None
                     else set(mesh.axis_names))
            traceable = jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                      out_specs=out_specs,
                                      axis_names=names, check_vma=False)
            inner = jax.jit(traceable, donate_argnums=donate_argnums,
                            **jit_kwargs)
        else:
            path = "jit"
            inner = jax.jit(fn, donate_argnums=donate_argnums,
                            **jit_kwargs)
            traceable = fn

        from ..core.flags import flag
        do_verify = flag("FLAGS_tpu_lint") if verify is None else verify

        def _lint(args, kwargs):
            self.verify_callable(traceable, *args, mesh=mesh,
                                 name=getattr(fn, "__name__", "plan_fn"),
                                 **kwargs)

        state = {"checked": not do_verify}
        if do_verify and example_args is not None:
            _lint(tuple(example_args), {})
            state["checked"] = True

        def compiled(*args, **kwargs):
            if not state["checked"]:
                _lint(args, kwargs)
                state["checked"] = True
            with mesh:
                return inner(*args, **kwargs)

        compiled.path = path
        compiled.mesh = mesh
        compiled.topology = topo
        compiled.jitted = inner
        compiled.plan = self
        return compiled

    def verify_callable(self, fn, *args, mesh=None, name=None, **kwargs):
        """Trace ``fn(*args)`` and run the SPMD collective-consistency
        rules (PR-8 checker). Raises :class:`PlanVerificationError` on
        error-severity findings; warnings (e.g. donation-sharding) pass
        through. Returns the full finding list."""
        from ..analysis.jaxpr_checks import lint_callable
        axis_names = (set(mesh.axis_names) if mesh is not None
                      else set(self.dims))
        findings = lint_callable(fn, *args, name=name,
                                 axis_names=axis_names, **kwargs)
        errors = _error_findings(findings)
        if errors:
            raise PlanVerificationError(errors)
        return findings

    # -- the llama training step --------------------------------------------
    def train_step(self, cfg, devices=None, *, optimizer=None, zero=True,
                   verify: Optional[bool] = None):
        """(step_fn, init_fn) for this plan: ``models.llama
        .build_train_step`` on the plan's topology, with the plan's
        schedule/microbatching/overlap, optionally gated through the
        SPMD checker on first call (verify=None → ``FLAGS_tpu_lint``).
        """
        from ..models.llama import build_train_step
        from ..core.flags import flag

        topo = self.topology(devices)
        use_pp = self.pp > 1 and self.schedule != "none"
        schedule = self.schedule if use_pp else "gpipe"
        n_micro = self.n_microbatches or (self.pp if use_pp else None)
        step_fn, init_fn = build_train_step(
            cfg, topo, optimizer=optimizer, use_pp=use_pp,
            n_microbatches=n_micro, zero=zero, schedule=schedule,
            overlap=self.overlap)

        do_verify = flag("FLAGS_tpu_lint") if verify is None else verify
        if not do_verify:
            step_fn.plan = self
            step_fn.plan_topology = topo
            return _wrap_step_tracing(self, step_fn), init_fn

        state = {"checked": False}
        inner = step_fn

        def verified_step(params, opt_state, batch):
            if not state["checked"]:
                with topo.mesh:
                    self.verify_callable(inner.jitted, params, opt_state,
                                         batch, mesh=topo.mesh,
                                         name="train_step")
                state["checked"] = True
            return inner(params, opt_state, batch)

        verified_step.jitted = inner.jitted
        verified_step.abstract_state = inner.abstract_state
        verified_step.batch_shardings = inner.batch_shardings
        verified_step.plan = self
        verified_step.plan_topology = topo
        return _wrap_step_tracing(self, verified_step), init_fn

    # -- spec round-trip ----------------------------------------------------
    def to_spec(self) -> Dict[str, Any]:
        spec = {"axes": self.dims, "schedule": self.schedule,
                "n_microbatches": self.n_microbatches,
                "overlap": self.overlap}
        if self.param_specs is not None:
            spec["param_specs"] = self.param_specs
        return spec

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "Plan":
        axes = dict(spec.get("axes", {}))
        kw = {a: int(axes.get(a, 1)) for a in AXES}
        return cls(schedule=spec.get("schedule", "none"),
                   n_microbatches=spec.get("n_microbatches"),
                   overlap=bool(spec.get("overlap", False)),
                   param_specs=spec.get("param_specs"), **kw)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_spec(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "Plan":
        with open(path) as f:
            return cls.from_spec(json.load(f))

    @classmethod
    def from_report(cls, report) -> "Plan":
        """Build a Plan from a pod_report: accepts the report dict, a
        path to the report JSON, a ``--plan-out`` spec dict, or a path
        to one. The planner's winning ``(dp, pp, sharding, mp)`` becomes
        the plan axes; ``pp > 1`` selects the 1F1B schedule with the
        report's microbatch count."""
        if isinstance(report, (str, os.PathLike)):
            with open(report) as f:
                report = json.load(f)
        if "axes" in report:  # already an executable plan spec
            return cls.from_spec(report)
        topo = report.get("topology")
        if topo is None:
            raise PlanError("report has no 'topology' section (and is "
                            "not a plan spec)")
        kw = {a: int(topo.get(a, 1)) for a in AXES}
        pp = kw["pp"]
        return cls(schedule="1f1b" if pp > 1 else "none",
                   n_microbatches=int(topo.get("n_microbatches", pp))
                   if pp > 1 else None,
                   overlap=True, **kw)

    # -- elasticity ---------------------------------------------------------
    def for_world_size(self, n: int) -> "Plan":
        """Refit the plan to ``n`` devices: keep the model axes
        (pp/sharding/sp/mp) and refit dp when they divide ``n``; else
        collapse to pure data parallelism (the always-valid fallback —
        params replicated, no pipeline)."""
        model = self.pp * self.sharding * self.sp * self.mp
        if n >= model and n % model == 0:
            return dataclasses.replace(self, dp=n // model)
        return dataclasses.replace(
            self, dp=n, pp=1, sharding=1, sp=1, mp=1,
            schedule="none", n_microbatches=None)

    def run_train_loop(self, cfg, batches: Iterable[Dict[str, Any]], *,
                       devices=None, optimizer=None, rng=None,
                       job_id: str = "plan", scale_store=None,
                       ckpt_root: Optional[str] = None,
                       verify: Optional[bool] = None,
                       on_step: Optional[Callable] = None):
        """Plan-driven training loop with elastic resize.

        Before each step the loop polls ``scale_store`` for the
        ``fleet.elastic.request_scale`` key of ``job_id``; on a changed
        world size it checkpoints (params + opt state), refits the plan
        with :meth:`for_world_size`, recompiles the step on the new
        device set, and restores via ``reshard.restore_resharded`` onto
        the new mesh — the PR-9 machinery, driven by the Plan.

        Returns ``{"losses", "world_sizes", "resizes"}`` (one entry per
        step; ``resizes`` records ``(step_index, old_world, new_world)``
        tuples).

        ``on_step(step_count, params, opt_state)`` fires after every
        completed step with the 1-based step count and the live state —
        the gang runtime's step-boundary hook (health step stamp +
        final-save snapshot + beacon).
        """
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .fault_tolerance import CheckpointManager
        from .reshard import restore_resharded
        from .fleet.elastic import _scale_key

        devices = list(devices if devices is not None else jax.devices())
        plan = self
        topo = plan.topology(devices)
        step_fn, init_fn = plan.train_step(cfg, devices,
                                           optimizer=optimizer,
                                           verify=verify)
        params, opt_state = init_fn(
            rng if rng is not None else jax.random.PRNGKey(0))

        def _poll_scale():
            if scale_store is None:
                return None
            try:
                raw = scale_store.get(_scale_key(job_id))
            except KeyError:
                return None
            if raw is None:
                return None
            if isinstance(raw, bytes):
                raw = raw.decode()
            return int(raw)

        def _place_like(state, abstract):
            # the pickle restore wraps leaves in the eager Tensor facade
            # (a pytree NODE) — unwrap to host arrays before re-placing
            # per the new step's shardings
            from ..core.tensor import Tensor
            state = jax.tree_util.tree_map(
                lambda x: np.asarray(getattr(x, "_array", x)),
                state, is_leaf=lambda x: isinstance(x, Tensor))
            return jax.tree_util.tree_map(
                lambda x, a: _put_global(x, a.sharding), state, abstract)

        history = {"losses": [], "world_sizes": [], "resizes": []}
        step_idx = 0
        # live observability: /healthz reports train progress when
        # FLAGS_tpu_metrics_port is set (no-op otherwise)
        _train_status = {"job_id": job_id, "step": 0, "loss": None,
                         "world_size": plan.world_size, "done": False}
        _exporter.maybe_serve("train", lambda: dict(_train_status))
        for batch in batches:
            want = _poll_scale()
            if (want is not None and want != plan.world_size
                    and want <= len(devices)):
                if ckpt_root is None:
                    raise PlanError(
                        "resize requested but run_train_loop was given "
                        "no ckpt_root to reshard through")
                mgr = CheckpointManager(ckpt_root, backend="pickle",
                                        sync=True)
                mgr.save(step_idx,
                         {"params": jax.tree_util.tree_map(
                             np.asarray, params),
                          "opt_state": jax.tree_util.tree_map(
                              np.asarray, opt_state)})
                old_world = plan.world_size
                plan = plan.for_world_size(want)
                topo = plan.topology(devices)
                step_fn, init_fn = plan.train_step(
                    cfg, devices, optimizer=optimizer, verify=verify)
                state, _ = restore_resharded(ckpt_root, mesh=topo.mesh)
                p_abs, o_abs = step_fn.abstract_state()
                params = _place_like(state["params"], p_abs)
                opt_state = _place_like(state["opt_state"], o_abs)
                history["resizes"].append((step_idx, old_world, want))
            sh = NamedSharding(topo.mesh, P(topo.batch_axes, None))
            placed = {k: _put_global(v, sh) for k, v in batch.items()}
            params, opt_state, metrics = step_fn(params, opt_state,
                                                 placed)
            history["losses"].append(float(metrics["loss"]))
            history["world_sizes"].append(plan.world_size)
            step_idx += 1
            if on_step is not None:
                on_step(step_idx, params, opt_state)
            _train_status.update(step=step_idx,
                                 loss=history["losses"][-1],
                                 world_size=plan.world_size)
        _train_status["done"] = True
        return history
