"""Device mesh & hybrid-parallel topology.

Reference analog: fleet's 4-D CommunicateTopology/HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py:53/:139) which builds one
NCCL ring per parallelism axis, and auto_parallel's ProcessMesh
(python/paddle/distributed/auto_parallel/process_mesh.py:45).

TPU-native: ONE jax.sharding.Mesh whose named axes ARE the process groups —
["dp", "sharding", "pp", "mp" (tensor), plus optional "sp"/"ep" folded into
mp/dp]. XLA inserts the collectives over ICI/DCN from PartitionSpec
annotations; ring ids / groups / streams all disappear.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["HybridTopology", "init_mesh", "get_mesh", "set_mesh",
           "ProcessMesh", "PartitionSpec", "NamedSharding"]

_GLOBAL_MESH: List[Optional[Mesh]] = [None]
_GLOBAL_TOPO: List[Optional["HybridTopology"]] = [None]


class HybridTopology:
    """CommunicateTopology analog: axis names + degrees over jax devices.

    order convention matches fleet: outermost "dp" (slowest-varying,
    cross-host/DCN friendly), innermost "mp" (fastest-varying — TP traffic
    stays on ICI neighbors), with "pp" and "sharding" in between
    (reference topology.py uses ["data","pipe","sharding","model"]).
    """

    AXES = ("dp", "pp", "sharding", "sp", "mp")

    def __init__(self, dp=1, pp=1, sharding=1, mp=1, devices=None, sp=1):
        devices = devices if devices is not None else jax.devices()
        want = dp * pp * sharding * sp * mp
        if want > len(devices):
            raise ValueError(
                f"topology {dp}x{pp}x{sharding}x{sp}x{mp}={want} needs "
                f"more than {len(devices)} devices")
        if want < len(devices) and dp == 1 and want == 1:
            dp = len(devices)  # default pure-DP over all devices
            want = dp
        devices = devices[:want]
        # "sp" (sequence/context parallel — ring attention) sits next to
        # "mp" so the ring's neighbor ppermute rides adjacent ICI links
        self.dims = {"dp": dp, "pp": pp, "sharding": sharding, "sp": sp,
                     "mp": mp}
        dev_array = np.asarray(devices).reshape(dp, pp, sharding, sp, mp)
        self.mesh = Mesh(dev_array, axis_names=self.AXES)

    # -- fleet-API parity ---------------------------------------------------
    def get_num_of_ranks(self, axis):
        return self.dims[axis]

    def world_size(self):
        return int(np.prod(list(self.dims.values())))

    def get_hybrid_group(self):
        return self.mesh

    @property
    def dp_degree(self):
        return self.dims["dp"]

    @property
    def pp_degree(self):
        return self.dims["pp"]

    @property
    def sharding_degree(self):
        return self.dims["sharding"]

    @property
    def sp_degree(self):
        return self.dims["sp"]

    @property
    def mp_degree(self):
        return self.dims["mp"]

    @property
    def batch_axes(self):
        """Mesh axes the global batch shards over: with a carved-out
        'sharding' (ZeRO) axis the data-parallel world is dp x sharding
        (fleet: sharding ranks consume distinct batches too)."""
        return ("dp", "sharding") if self.dims["sharding"] > 1 else "dp"

    def spec(self, *axes) -> PartitionSpec:
        return PartitionSpec(*axes)

    def sharding_for(self, *axes) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*axes))


def init_mesh(dp=1, pp=1, sharding=1, mp=1, devices=None,
              sp=1) -> HybridTopology:
    topo = HybridTopology(dp, pp, sharding, mp, devices, sp=sp)
    _GLOBAL_TOPO[0] = topo
    _GLOBAL_MESH[0] = topo.mesh
    return topo


def set_mesh(mesh: Mesh):
    _GLOBAL_MESH[0] = mesh


def get_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH[0]


def get_topology() -> Optional[HybridTopology]:
    if _GLOBAL_TOPO[0] is None:
        init_mesh()
    return _GLOBAL_TOPO[0]


class ProcessMesh:
    """auto_parallel.ProcessMesh parity: an N-D array of ranks with named
    dims, convertible to a jax Mesh."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            if process_ids is None:
                process_ids = np.arange(int(np.prod(shape)))
            arr = np.asarray(process_ids).reshape(shape)
        self._shape = list(arr.shape)
        self._process_ids = arr.reshape(-1).tolist()
        self._dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        self._rank_array = arr

    @property
    def shape(self):
        return list(self._shape)

    @property
    def process_ids(self):
        return list(self._process_ids)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def ndim(self):
        return len(self._shape)

    def to_jax_mesh(self) -> Mesh:
        devs = jax.devices()
        dev_array = np.asarray([devs[r % len(devs)]
                                for r in self._process_ids]).reshape(
            self._shape)
        return Mesh(dev_array, axis_names=tuple(self._dim_names))

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._shape == other._shape
                and self._process_ids == other._process_ids)

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, "
                f"dim_names={self._dim_names})")
