"""Real multi-process gang runtime: Plan execution across process
boundaries.

Reference analog: the launch controller + fleet elastic manager pair —
a pod of gang-scheduled trainer processes where any worker death tears
the pod down and the manager relaunches it as a unit. PR 13's Plan
reproduced the schedule and overlap inside ONE process; this module
promotes it to an actual ``python -m paddle_tpu.distributed.launch``
pod: N worker processes rendezvous over the launcher's TCPStore,
bootstrap ``jax.distributed`` (gloo CPU collectives on the test
backend, ICI on real TPU slices), and each rank binds its
HealthMonitor / Watchdog / TraceRecorder to its real pid.

One rank's lifecycle::

    ctx = gang.init_gang()              # store + jax.distributed + mesh
                                        # + health monitor, all wired
    plan = Plan(...)                    # any Plan; world = all procs
    with ctx.running():                 # failure -> save -> exit 101
        plan.run_train_loop(cfg, batches, on_step=ctx.step_boundary,
                            ckpt_root=ctx.config.ckpt_root)
    ctx.shutdown(0)                     # sidecars + ordered teardown

Failure semantics (the headline): when a REAL peer dies or hangs
mid-collective, every surviving rank detects it within the heartbeat /
collective-beacon deadline (runtime/health.py, PR 7), writes a final
step-boundary checkpoint from the state snapshot ``step_boundary``
handed over, flushes its incident + trace sidecars, and exits 101 —
the cooperative relaunch code the elastic launcher honors without
burning restart budget. The relaunched generation restores through
``reshard.restore_resharded`` (possibly at a different world size) and
resumes the trajectory.

The flight recorder is the correctness oracle: each rank writes a
trace sidecar ending in the :data:`profiler.trace.TERMINAL_BARRIER`
barrier; ``tools/trace_report.py --gang`` merges the per-rank sidecars
and fails the run when any rank's recorded 1F1B schedule diverges from
the static ``overlap.schedule_events`` model, or any rank is missing
its sidecar / terminal barrier.

``python -m paddle_tpu.distributed.gang`` is the runnable preset: the
bench multichip llama config driven through ``Plan.run_train_loop``
under a real gang, printing one ``GANG_RESULT {json}`` line per rank
(``bench.py --multichip --gang N`` parses these into the perf ledger).
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import socket
import sys
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Optional

from ..profiler import trace as _trace
from ..runtime import health as _health
from ..runtime.watchdog import (Watchdog, incidents, persist_incidents,
                                record_incident)
from ..testing import chaos as _chaos

__all__ = ["GangConfig", "GangContext", "init_gang", "main"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclasses.dataclass
class GangConfig:
    """Tunables for one gang worker. ``from_env`` reads the
    ``PTQ_GANG_*`` overrides the launcher/test environment passes down
    (every knob also has a constructor default sized for real pods —
    tests shrink the deadlines to keep E2Es fast)."""

    heartbeat_interval: float = 1.0
    heartbeat_timeout: float = 5.0
    collective_deadline: Optional[float] = None  # None -> watchdog flag
    straggler_skew: int = 5
    rendezvous_timeout: float = 60.0
    coordinator_host: str = "127.0.0.1"
    trace_dir: Optional[str] = None
    ckpt_root: Optional[str] = None
    # chaos `kill` rules become os._exit (sudden real peer death) rather
    # than an in-process ReplicaKilled exception
    process_kill_mode: bool = True
    # also beat the fleet.elastic hb keys so a launcher started with
    # --heartbeat_timeout can declare the whole pod hung
    launcher_heartbeat: bool = True

    _ENV = {
        "PTQ_GANG_HEARTBEAT_INTERVAL": ("heartbeat_interval", float),
        "PTQ_GANG_HEARTBEAT_TIMEOUT": ("heartbeat_timeout", float),
        "PTQ_GANG_COLLECTIVE_DEADLINE": ("collective_deadline", float),
        "PTQ_GANG_STRAGGLER_SKEW": ("straggler_skew", int),
        "PTQ_GANG_RENDEZVOUS_TIMEOUT": ("rendezvous_timeout", float),
        "PTQ_GANG_COORD_HOST": ("coordinator_host", str),
        "PTQ_GANG_TRACE_DIR": ("trace_dir", str),
        "PTQ_GANG_CKPT_ROOT": ("ckpt_root", str),
    }

    @classmethod
    def from_env(cls, **overrides) -> "GangConfig":
        kw: Dict[str, Any] = {}
        for var, (field, cast) in cls._ENV.items():
            # one-shot bootstrap read, not a hot path
            raw = os.environ.get(var)  # tpu-lint: disable=flag-lookup-in-loop
            if raw:
                kw[field] = cast(raw)
        kw.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**kw)


class GangContext:
    """One rank's handle on a live gang: the rendezvous store, the
    health monitor bound to this process, the final-save snapshot box,
    and the teardown protocol."""

    def __init__(self, config: GangConfig, store, rank: int,
                 world_size: int, restart: int, job_id: str,
                 owns_store: bool = False):
        self.config = config
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.restart = int(restart)
        self.job_id = job_id
        self.pid = os.getpid()
        self.monitor: Optional[_health.HealthMonitor] = None
        self.watchdog: Optional[Watchdog] = None
        self._owns_store = owns_store
        self._hb_stop = None
        self._final_box: Dict[str, Any] = {}
        self._finalized = False

    # -- training-loop integration ------------------------------------------

    def step_boundary(self, step: int, params=None, opt_state=None):
        """Per-step hook (``run_train_loop(on_step=...)`` shape): stamp
        the health step, hand the just-completed state to the
        final-save box, record the step barrier, and pass through the
        gang's per-step sync point.

        Ordering matters: the step stamp and the state snapshot land
        BEFORE the eager ``all_reduce`` below — that call is identity
        outside a trace but fires the health collective beacon and the
        ``collective.all_reduce`` chaos point, so a ``kill@``/``hang@``
        rule matching this step bites a rank whose snapshot already
        holds this step's state (survivors and self-detectors then
        final-save exactly the crash-step checkpoint)."""
        if self.monitor is not None:
            self.monitor.set_step(int(step))
        else:
            _health.set_step(int(step))
        if params is not None:
            self._final_box = {"step": int(step), "params": params,
                               "opt_state": opt_state}
        _trace.barrier(f"gang/step{step}")
        import numpy as np
        from ..core.tensor import to_tensor
        from .collective import all_reduce
        all_reduce(to_tensor(np.zeros((), np.float32)))

    def final_save(self):
        """Write the last step-boundary snapshot as a committed
        checkpoint. Runs on the MONITOR thread during failure
        conversion (the main thread may be hung inside a collective),
        so it only touches state handed over at step boundaries —
        already-computed arrays that fetch without any collective."""
        box = self._final_box
        root = self.config.ckpt_root
        if not box or not root:
            return
        if self.world_size > 1:
            # gang coordination: first claimant owns the step's save —
            # survivors all hold identical (replicated) state, so one
            # commit suffices and concurrent commits to one root would
            # race on the step's tmp dir. Store down -> save anyway:
            # worst case is a racy duplicate, never a lost checkpoint.
            try:
                claim = self.store.add(
                    f"gang/save/{self.restart}/{box['step']}", 1)
                if claim > 1:
                    return
            except Exception:  # tpu-lint: disable=except-pass
                pass
        import jax
        from .fault_tolerance import CheckpointManager
        from .reshard import host_full
        state = {
            "params": jax.tree_util.tree_map(host_full, box["params"]),
            "opt_state": jax.tree_util.tree_map(host_full,
                                                box["opt_state"]),
        }
        CheckpointManager(root, backend="pickle",
                          sync=True).save(box["step"], state)

    @contextmanager
    def running(self):
        """Scope the training loop: an exception escaping it (a gloo
        collective erroring out under a dead peer, a poisoned step)
        converts to the save-and-exit-101 path instead of an arbitrary
        crash code."""
        try:
            yield self
        except SystemExit:
            raise
        except BaseException as exc:  # noqa: B036 — must catch KeyboardInterrupt too
            self.abort(f"{type(exc).__name__}: {exc}")

    # -- failure conversion --------------------------------------------------

    def abort(self, reason: str):
        """Main-thread failure path: record, then route through the
        monitor's conversion (final save + gang fail flag + incident
        flush + exit 101). If another thread already converted, wait
        for its exit; a hard exit-101 backstop guarantees this call
        never returns."""
        record_incident("gang_abort", reason=str(reason)[-500:],
                        gang_rank=self.rank)
        m = self.monitor
        if m is not None:
            m._convert(f"rank {self.rank}: {reason}")
            # _convert returned -> a conversion is already in flight on
            # the monitor thread; give it time to save and exit us
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                time.sleep(0.1)
        try:
            self.final_save()
        except Exception as e:
            record_incident("final_save_failed", error=str(e)[-500:])
        try:
            persist_incidents()
        except OSError:
            pass
        os._exit(_health.RELAUNCH_EXIT_CODE)

    # -- teardown ------------------------------------------------------------

    def finalize(self, status: str = "ok"):
        """Flush this rank's flight-recorder sidecar (terminal barrier
        last) and stop the background threads. Idempotent; does not
        exit. The incident buffer is only persisted when non-empty so a
        clean relaunched generation never clobbers the previous
        generation's post-mortem files."""
        if self._finalized:
            return
        self._finalized = True
        if _trace.enabled():
            _trace.barrier(_trace.TERMINAL_BARRIER, status=status,
                           step=(self._final_box or {}).get("step"))
            if self.config.trace_dir:
                os.makedirs(self.config.trace_dir, exist_ok=True)
                _trace.write_sidecar(
                    _trace.sidecar_path(self.config.trace_dir, self.rank),
                    extra={"world_size": self.world_size,
                           "restart": self.restart, "status": status})
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self.monitor is not None:
            self.monitor.stop()
            if _health.get() is self.monitor:
                _health.uninstall()
        if incidents():
            try:
                persist_incidents()
            except OSError:
                pass

    def shutdown(self, exit_code: int = 0):
        """Orderly gang teardown: finalize sidecars, align every rank
        on the exit barrier, then detach from the store and the jax
        coordination service (whose own shutdown barrier holds the
        coordinator open until every client disconnected)."""
        self.finalize(status="ok" if exit_code == 0
                      else f"exit{exit_code}")
        if self.world_size > 1:
            try:
                self.store.barrier(f"gang/done/{self.restart}",
                                   rank=self.rank,
                                   timeout=self.config.rendezvous_timeout)
            except Exception as e:  # peers died mid-exit: still leave
                sys.stderr.write(f"gang: exit barrier skipped: {e}\n")
        try:
            self.store.close()
        except Exception:  # tpu-lint: disable=except-pass
            pass
        from .parallel import shutdown as _dist_shutdown
        _dist_shutdown()


def _init_jax_distributed(store, rank: int, world: int, restart: int,
                          cfg: GangConfig):
    """Multi-client bootstrap: rank 0 publishes a coordinator address
    on the rendezvous store, every rank joins ``jax.distributed``. On
    the CPU test backend cross-process collectives need the gloo
    implementation — selected here iff the backend is not yet
    initialized (tier-1 in-process callers skip this whole path)."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # backend already initialized or option not present
    except Exception:  # tpu-lint: disable=except-pass
        pass
    key = f"gang/coord/{restart}"
    if rank == 0:
        coord = f"{cfg.coordinator_host}:{_free_port()}"
        store.set(key, coord.encode())
    else:
        coord = store.wait(key, cfg.rendezvous_timeout).decode()
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=world, process_id=rank)
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise
    if jax.process_count() != world:
        raise RuntimeError(
            f"gang bootstrap mismatch: jax sees "
            f"{jax.process_count()} processes, launcher promised {world}")


def init_gang(config: Optional[GangConfig] = None) -> GangContext:
    """Bring this process up as one rank of a real gang.

    Reads the launcher env contract (PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_MASTER / PADDLE_RESTART_COUNT), joins
    the rendezvous store, runs the named-rank boot barrier (a wedged
    peer is called out BY RANK in the TimeoutError), bootstraps
    ``jax.distributed`` + the global mesh, and starts the
    HealthMonitor bound to this real pid. Single-process (world 1, no
    PADDLE_MASTER) degrades to a self-owned store with the same API so
    unit tests and notebooks run the identical code path."""
    cfg = config if config is not None else GangConfig.from_env()
    env = os.environ
    rank = int(env.get("PADDLE_TRAINER_ID", "0"))
    world = int(env.get("PADDLE_TRAINERS_NUM", "1"))
    restart = int(env.get("PADDLE_RESTART_COUNT", "0"))
    job_id = env.get("PADDLE_JOB_ID", "gang")
    master = env.get("PADDLE_MASTER")

    if cfg.process_kill_mode:
        _chaos.set_kill_mode("process")

    from .store import TCPStore
    owns = False
    if master and world > 1:
        host, port = master.rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=False,
                         world_size=world,
                         timeout=cfg.rendezvous_timeout)
    else:
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         world_size=world,
                         timeout=cfg.rendezvous_timeout)
        owns = True

    wd = Watchdog(deadlines={"gang.rendezvous": cfg.rendezvous_timeout})
    with wd.phase("gang.rendezvous"):
        store.barrier(f"gang/boot/{restart}", rank=rank,
                      timeout=cfg.rendezvous_timeout)
        if world > 1:
            _init_jax_distributed(store, rank, world, restart, cfg)
    from . import parallel as _parallel
    from .mesh import init_mesh
    init_mesh()
    # later init_parallel_env() calls must no-op: the gang already owns
    # the jax.distributed bootstrap (re-initializing would fail)
    _parallel._INITIALIZED[0] = True

    ctx = GangContext(cfg, store, rank, world, restart, job_id,
                      owns_store=owns)
    ctx.watchdog = wd

    monitor = _health.HealthMonitor(
        store, rank, world, job_id=job_id, restart=restart,
        heartbeat_interval=cfg.heartbeat_interval,
        heartbeat_timeout=cfg.heartbeat_timeout,
        collective_deadline=cfg.collective_deadline,
        straggler_skew=cfg.straggler_skew)
    monitor.register_final_save(ctx.final_save)
    _health.install(monitor)
    monitor.start()
    ctx.monitor = monitor

    if cfg.launcher_heartbeat and master and world > 1:
        from .fleet.elastic import start_heartbeat
        ctx._hb_stop = start_heartbeat(cfg.heartbeat_interval,
                                       store=store)

    _trace.barrier(f"gang/boot{restart}", rank_pid=ctx.pid)
    return ctx


# ---------------------------------------------------------------------------
# runnable preset: the bench multichip llama config under a real gang
# ---------------------------------------------------------------------------

def _preset_result(ctx: GangContext, plan, history,
                   step_ms: float) -> Dict[str, Any]:
    from .overlap import schedule_events
    matches = None
    if _trace.enabled() and plan.pp > 1:
        recorded = _trace.pipeline_schedule_events(_trace.events())
        static = schedule_events(plan.pp,
                                 plan.n_microbatches or plan.pp,
                                 overlap=plan.overlap)
        matches = recorded == static
    return {
        "rank": ctx.rank, "pid": ctx.pid,
        "world_size": ctx.world_size, "restart": ctx.restart,
        "plan": plan.dims, "schedule": plan.schedule,
        "n_microbatches": plan.n_microbatches,
        "overlap": plan.overlap,
        "steps": len(history["losses"]),
        "losses": [float(x) for x in history["losses"]],
        "step_ms": round(step_ms, 2),
        "matches_static": matches,
    }


def main(argv=None) -> int:
    """``python -m paddle_tpu.distributed.gang``: run the multichip
    llama preset through ``Plan.run_train_loop`` under a real gang and
    print one ``GANG_RESULT {json}`` line (parsed by ``bench.py
    --multichip --gang N`` and the gang E2E tests). The pipeline spans
    the processes: with N ranks of one device each, ``pp=N`` 1F1B p2p
    crosses real process boundaries."""
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.gang")
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--trace-out", default=None,
                   help="flight-recorder sidecar dir (enables tracing)")
    p.add_argument("--ckpt-root", default=None)
    p.add_argument("--n-micro", type=int, default=4)
    p.add_argument("--no-overlap", action="store_true")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=32)
    args = p.parse_args(argv)

    from ..core.flags import set_flags
    set_flags({"FLAGS_tpu_trace": args.trace_out is not None})

    cfg = GangConfig.from_env(trace_dir=args.trace_out,
                              ckpt_root=args.ckpt_root)
    ctx = init_gang(cfg)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models.llama import LlamaConfig
    from .plan import Plan

    ndev = jax.device_count()
    model_cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=64,
        dtype=jnp.float32, use_remat=False)
    if ndev > 1:
        plan = Plan(pp=ndev, schedule="1f1b",
                    n_microbatches=args.n_micro,
                    overlap=not args.no_overlap)
    else:
        plan = Plan()

    rng = np.random.default_rng(0)
    B, S = args.batch, args.seq
    batches = [{
        "input_ids": rng.integers(0, model_cfg.vocab_size, (B, S),
                                  dtype=np.int32),
        "labels": rng.integers(0, model_cfg.vocab_size, (B, S),
                               dtype=np.int32),
    } for _ in range(args.steps)]

    t0 = time.perf_counter()
    with ctx.running():
        history = plan.run_train_loop(
            model_cfg, batches, on_step=ctx.step_boundary,
            ckpt_root=args.ckpt_root, verify=False)
    step_ms = (time.perf_counter() - t0) / max(1, args.steps) * 1e3

    result = _preset_result(ctx, plan, history, step_ms)
    print("GANG_RESULT " + json.dumps(result, sort_keys=True),
          flush=True)
    ctx.shutdown(0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
