"""PS-era dataset surface: InMemoryDataset / QueueDataset + table
entry configs.

Reference analog: python/paddle/distributed/fleet/dataset/dataset.py
(DatasetBase/InMemoryDataset/QueueDataset over the C++ MultiSlotDataset
feeders) and the sparse-table accessor entry configs
(CountFilterEntry etc. in distributed/ps/the_one_ps.py).

TPU-native scope: the reference couples these to its C++ data-feed +
PS runtime; here they are honest host-side file datasets that plug
into ``paddle.io.DataLoader`` (and the HostEmbedding PS capability):
``set_filelist`` names text files, ``load_into_memory`` materializes
lines (InMemoryDataset) or leaves them streaming (QueueDataset), and
``slot`` parsing splits whitespace-delimited records. pipe_command
shelling is intentionally unsupported — pass a python ``parse_fn``
instead (raises with that guidance if configured).
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional

__all__ = ["InMemoryDataset", "QueueDataset", "CountFilterEntry",
           "ShowClickEntry", "ProbabilityEntry", "ParallelMode",
           "is_available"]


class _Entry:
    """Sparse-table accessor entry config (tiny value object)."""

    def __init__(self, **kw):
        self._config = dict(kw)

    def __repr__(self):
        kv = ", ".join(f"{k}={v}" for k, v in self._config.items())
        return f"{type(self).__name__}({kv})"


class CountFilterEntry(_Entry):
    """reference: show/click count threshold filter for sparse ids."""

    def __init__(self, count_filter_threshold=0.7):
        super().__init__(count_filter_threshold=count_filter_threshold)


class ShowClickEntry(_Entry):
    """reference: names the show/click input slots of a CTR accessor."""

    def __init__(self, show_slot="show", click_slot="click"):
        super().__init__(show_slot=show_slot, click_slot=click_slot)


class ProbabilityEntry(_Entry):
    """reference: probabilistic admission of new sparse ids."""

    def __init__(self, probability=1.0):
        super().__init__(probability=probability)


class _FileDataset:
    def __init__(self):
        self._filelist: List[str] = []
        self._parse_fn: Optional[Callable[[str], object]] = None
        self._batch_size = 1
        self._lines: Optional[List[object]] = None

    def init(self, batch_size=1, use_var=None, pipe_command=None,
             parse_fn=None, **kwargs):
        if pipe_command:
            raise NotImplementedError(
                "pipe_command shells a C++ data feed in the reference; "
                "pass parse_fn=<callable(line) -> sample> instead")
        self._batch_size = int(batch_size)
        self._parse_fn = parse_fn
        return self

    # paddle's private-config spelling
    _init_distributed_settings = init

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _iter_lines(self):
        for path in self._filelist:
            if not os.path.exists(path):
                raise FileNotFoundError(path)
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    yield self._parse_fn(line) if self._parse_fn else line


class InMemoryDataset(_FileDataset):
    """Materializes every record in host RAM (the shuffle-capable
    variant; reference dataset.py InMemoryDataset)."""

    def load_into_memory(self):
        self._lines = list(self._iter_lines())

    def get_memory_data_size(self):
        return len(self._lines or [])

    def local_shuffle(self, seed=0):
        import random
        if self._lines is None:
            raise RuntimeError("call load_into_memory() first")
        random.Random(seed).shuffle(self._lines)

    global_shuffle = local_shuffle  # one-host build: same pool

    def release_memory(self):
        self._lines = None

    def __len__(self):
        if self._lines is None:
            raise RuntimeError("call load_into_memory() first")
        return len(self._lines)

    def __getitem__(self, i):
        if self._lines is None:
            raise RuntimeError("call load_into_memory() first")
        return self._lines[i]


class QueueDataset(_FileDataset):
    """Streams records file-by-file without materializing (reference
    QueueDataset): an iterable dataset for paddle.io.DataLoader."""

    def __iter__(self):
        return self._iter_lines()


class ParallelMode:
    """reference: distributed/parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def is_available():
    """reference: distributed.is_available — the communication package
    is always built into this stack."""
    return True
