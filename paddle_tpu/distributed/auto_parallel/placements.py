"""Placement types describing how a tensor maps onto a ProcessMesh.

Reference analog: auto_parallel's dist_attr dims_mapping
(paddle/fluid/distributed/auto_parallel/dist_attr.h) — dims_mapping[i] = j
means tensor dim i is split over mesh dim j, -1 means replicated. The
Shard/Replicate/Partial vocabulary is the modern spelling of the same
thing; `to_partition_spec` lowers a placements list (one entry per MESH
dim, reference convention) to the jax PartitionSpec GSPMD consumes.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec

__all__ = ["Placement", "Shard", "Replicate", "Partial",
           "to_partition_spec"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Tensor dim `dim` is split across the corresponding mesh axis."""

    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending-reduction state (reference: partial status in dist_attr).
    GSPMD materialises/reduces partials automatically; tensors annotated
    Partial are treated as replicated at placement time."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return (isinstance(other, Partial)
                and other.reduce_type == self.reduce_type)

    def __hash__(self):
        return hash(("Partial", self.reduce_type))

    def __repr__(self):
        return f"Partial({self.reduce_type})"


def to_partition_spec(placements, mesh, ndim=None):
    """placements[i] describes how mesh axis i touches the tensor
    (reference convention: one placement per mesh dimension). Returns the
    PartitionSpec (one entry per TENSOR dimension) GSPMD wants."""
    axis_names = list(mesh.axis_names) if hasattr(mesh, "axis_names") \
        else list(mesh.dim_names)
    if ndim is None:
        ndim = 1 + max((p.dim for p in placements
                        if isinstance(p, Shard)), default=-1)
    dims = [None] * ndim
    for axis_name, p in zip(axis_names, placements):
        if isinstance(p, Shard):
            if dims[p.dim] is not None:
                # two mesh axes on one tensor dim → tuple (nested sharding)
                prev = dims[p.dim]
                dims[p.dim] = (prev if isinstance(prev, tuple)
                               else (prev,)) + (axis_name,)
            else:
                dims[p.dim] = axis_name
    return PartitionSpec(*dims)
