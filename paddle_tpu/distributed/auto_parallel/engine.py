"""auto_parallel.Engine — annotate → compile → run.

Reference analog: python/paddle/distributed/auto_parallel/engine.py
(Engine at :57; fit:812, evaluate:982, predict:1092, prepare:1273,
save:1563, load:1646, cost:1698). The reference's four-stage pipeline
(_build dy2static trace → _plan Completer → _parallel Partitioner+Resharder
→ _initialize comm groups, engine.py:503) collapses here to: place params
on the mesh per annotation, shard the batch over "dp", and `jax.jit` the
whole training step — XLA's SPMD partitioner performs the completion/
partition/reshard stages (SURVEY.md §3.6).

Execution model: the first step runs eagerly through the Tensor tape
(this concretely materialises optimizer accumulators, fixing the state
schema); every later step runs through one compiled XLA program that
threads (param arrays, optimizer-state arrays, step count) with buffer
donation — the _ExecutorCache/InterpreterCore analog.
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor, no_grad
from ..mesh import get_mesh, init_mesh, ProcessMesh
from ..shard import shard_params
from .strategy import Strategy

__all__ = ["Engine"]


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = list(metrics) if isinstance(metrics, (list, tuple)) \
            else ([metrics] if metrics is not None else [])
        self._strategy = strategy or Strategy()
        self._mesh = None
        self._prepared = False
        self._jit_train = None
        self._jit_eval = None
        self._jit_pred = None
        self._params: List[Tensor] = []
        self._acc_schema = None
        self.history = {"loss": []}

    # -- build ------------------------------------------------------------
    def prepare(self, inputs_spec=None, labels_spec=None, main_program=None,
                startup_program=None, mode="train"):
        """Place parameters on the mesh (the Partitioner stage)."""
        if self._prepared:
            return
        mesh = get_mesh()
        if mesh is None:
            mesh = init_mesh().mesh  # pure-DP default over all devices
        self._mesh = mesh
        shard_params(self._model, mesh)
        self._params = list(self._model.parameters())
        self._prepared = True

    def _data_sharding(self, arr):
        ndim = getattr(arr, "ndim", 0)
        spec = PartitionSpec(*(["dp"] + [None] * (ndim - 1))) if ndim \
            else PartitionSpec()
        return NamedSharding(self._mesh, spec)

    def _put_batch(self, arrays):
        if not self._strategy.split_data:
            return arrays
        dp = self._mesh.shape.get("dp", 1)
        out = []
        for a in arrays:
            if dp > 1 and a.ndim and a.shape[0] % dp == 0:
                a = jax.device_put(a, self._data_sharding(a))
            out.append(a)
        return out

    # -- the compiled step -------------------------------------------------
    def _snapshot_accs(self):
        """Flatten optimizer accumulators into a stable (schema, arrays)
        pair; schema entries are (acc_name, param_index)."""
        opt = self._optimizer
        pid_to_idx = {id(p): i for i, p in enumerate(self._params)}
        schema, arrays = [], []
        for name in sorted(opt._accumulators):
            store = opt._accumulators[name]
            for pid in sorted(store, key=lambda q: pid_to_idx.get(q, -1)):
                if pid in pid_to_idx:
                    schema.append((name, pid_to_idx[pid]))
                    arrays.append(store[pid])
        return schema, arrays

    def _install_accs(self, schema, arrays):
        opt = self._optimizer
        accs = {}
        for (name, idx), arr in zip(schema, arrays):
            accs.setdefault(name, {})[id(self._params[idx])] = arr
        opt._accumulators = accs

    @staticmethod
    def _single(outs):
        if isinstance(outs, (tuple, list)) and len(outs) == 1:
            return outs[0]
        return outs

    def _eager_step(self, ins, labels):
        model, opt = self._model, self._optimizer
        model.train()
        outs = self._single(model(*ins))
        loss = self._loss(outs, *labels) if self._loss is not None else outs
        if isinstance(loss, (tuple, list)):
            loss = loss[0]
        loss.backward()
        opt.step()
        opt.clear_grad()
        self._update_metrics(outs, labels)
        return float(loss.item())

    def _build_jit_train(self, n_ins):
        model, opt = self._model, self._optimizer
        params = self._params
        schema = self._acc_schema

        def step(param_arrays, acc_arrays, tcount, *data):
            saved = [p._array for p in params]
            saved_accs, saved_t = opt._accumulators, opt._step_count
            try:
                for p, a in zip(params, param_arrays):
                    p._set_array(a)
                self._install_accs(schema, list(acc_arrays))
                opt._step_count = tcount
                ins = [Tensor(a, stop_gradient=True) for a in data[:n_ins]]
                labels = [Tensor(a, stop_gradient=True)
                          for a in data[n_ins:]]
                model.train()
                outs = self._single(model(*ins))
                loss = self._loss(outs, *labels) if self._loss is not None \
                    else outs
                if isinstance(loss, (tuple, list)):
                    loss = loss[0]
                loss.backward()
                opt.step()
                opt.clear_grad()
                _, new_accs = self._snapshot_accs()
                return ([p._array for p in params], new_accs,
                        opt._step_count, loss._array)
            finally:
                for p, a in zip(params, saved):
                    p._set_array(a)
                opt._accumulators, opt._step_count = saved_accs, saved_t

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_jit_eval(self, n_ins, with_loss):
        model = self._model
        params = self._params

        def step(param_arrays, *data):
            saved = [p._array for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._set_array(a)
                ins = [Tensor(a, stop_gradient=True) for a in data[:n_ins]]
                labels = [Tensor(a, stop_gradient=True)
                          for a in data[n_ins:]]
                model.eval()
                with no_grad():
                    outs = self._single(model(*ins))
                    if not with_loss or self._loss is None:
                        return tuple(o._array for o in (
                            outs if isinstance(outs, (tuple, list))
                            else [outs]))
                    loss = self._loss(outs, *labels)
                    if isinstance(loss, (tuple, list)):
                        loss = loss[0]
                    outs_t = outs if isinstance(outs, (tuple, list)) \
                        else [outs]
                    return (loss._array,) + tuple(o._array for o in outs_t)
            finally:
                for p, a in zip(params, saved):
                    p._set_array(a)

        return jax.jit(step)

    def _train_batch(self, ins_np, labels_np):
        """One optimizer step: eager on the first call (materialises the
        optimizer-state schema), compiled afterwards."""
        data = self._put_batch([jnp.asarray(np.asarray(x))
                                for x in ins_np + labels_np])
        if self._acc_schema is None:
            ins = [Tensor(a, stop_gradient=True)
                   for a in data[:len(ins_np)]]
            labels = [Tensor(a, stop_gradient=True)
                      for a in data[len(ins_np):]]
            loss = self._eager_step(ins, labels)
            self._acc_schema, _ = self._snapshot_accs()
            self._jit_train = self._build_jit_train(len(ins_np))
            return loss
        _, accs = self._snapshot_accs()
        new_p, new_accs, tcount, loss = self._jit_train(
            [p._array for p in self._params], accs,
            jnp.asarray(self._optimizer._step_count, jnp.int32),
            *data)
        for p, a in zip(self._params, new_p):
            p._set_array(a)
        self._install_accs(self._acc_schema, new_accs)
        self._optimizer._step_count = tcount
        return float(loss)

    # -- metrics -----------------------------------------------------------
    def _update_metrics(self, outs, labels):
        if not self._metrics:
            return
        outs_t = outs if isinstance(outs, (tuple, list)) else [outs]
        # compute every metric's stats device-side first, then fetch them
        # in ONE jax.device_get — a per-metric .numpy() was a blocking
        # device->host round-trip on every train step
        corrs = [m.compute(outs_t[0], *labels) for m in self._metrics]
        host = jax.device_get([c._array if isinstance(c, Tensor) else c
                               for c in corrs])
        for m, h in zip(self._metrics, host):
            m.update(h)

    # -- loops -------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers=0,
                   collate_fn=None):
        from ...io.dataloader import DataLoader
        if data is None or isinstance(data, DataLoader) \
                or hasattr(data, "__next__"):
            return data
        if hasattr(data, "__getitem__"):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers,
                              collate_fn=collate_fn)
        return data

    @staticmethod
    def _split(batch, sample_split):
        items = list(batch) if isinstance(batch, (tuple, list)) else [batch]
        if sample_split is None:
            sample_split = len(items) - 1 if len(items) > 1 else len(items)
        return items[:sample_split], items[sample_split:]

    def fit(self, train_data, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, save_dir=None,
            save_freq=1, valid_data=None, valid_sample_split=None,
            valid_freq=1, valid_steps=None, collate_fn=None,
            callbacks=None, verbose=2, num_workers=0):
        """reference: engine.py:812."""
        self.prepare()
        loader = self._as_loader(train_data, batch_size, True, num_workers,
                                 collate_fn)
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            t0, losses = time.time(), []
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                ins, labels = self._split(batch, train_sample_split)
                loss = self._train_batch(ins, labels)
                losses.append(loss)
                if verbose and step % log_freq == 0:
                    print(f"[auto_parallel] epoch {epoch} step {step} "
                          f"loss {loss:.4f}", flush=True)
            lr = getattr(self._optimizer, "_lr", None)
            if hasattr(lr, "step"):
                lr.step()
            self.history["loss"].append(float(np.mean(losses)))
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                self.evaluate(valid_data, valid_sample_split, batch_size,
                              steps=valid_steps, verbose=verbose)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if verbose:
                print(f"[auto_parallel] epoch {epoch} done "
                      f"{time.time() - t0:.1f}s mean loss "
                      f"{self.history['loss'][-1]:.4f}", flush=True)
        return self.history

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, collate_fn=None, callbacks=None,
                 verbose=2, num_workers=0):
        """reference: engine.py:982."""
        self.prepare()
        loader = self._as_loader(valid_data, batch_size, False, num_workers,
                                 collate_fn)
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            if steps is not None and step >= steps:
                break
            ins, labels = self._split(batch, valid_sample_split)
            data = self._put_batch(
                [jnp.asarray(np.asarray(x)) for x in ins + labels])
            if self._jit_eval is None:
                self._jit_eval = self._build_jit_eval(len(ins),
                                                      with_loss=True)
            out = self._jit_eval([p._array for p in self._params], *data)
            losses.append(float(out[0]))
            outs_t = [Tensor(o) for o in out[1:]]
            self._update_metrics(outs_t, [Tensor(x) for x in data[len(ins):]])
        res = {"loss": float(np.mean(losses)) if losses else None}
        for m in self._metrics:
            res[m.name() if callable(getattr(m, "name", None)) else "metric"]\
                = m.accumulate()
        if verbose:
            print(f"[auto_parallel] eval {res}", flush=True)
        return res

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=2,
                num_workers=0):
        """reference: engine.py:1092."""
        self.prepare()
        loader = self._as_loader(test_data, batch_size, False, num_workers,
                                 collate_fn)
        outputs = []
        for step, batch in enumerate(loader):
            if steps is not None and step >= steps:
                break
            ins, _ = self._split(batch, test_sample_split)
            data = self._put_batch([jnp.asarray(np.asarray(x))
                                    for x in ins])
            if self._jit_pred is None:
                self._jit_pred = self._build_jit_eval(len(ins),
                                                      with_loss=False)
            out = self._jit_pred([p._array for p in self._params], *data)
            outputs.append([np.asarray(o) for o in out])
        return outputs

    # -- io ----------------------------------------------------------------
    def save(self, path, training=True):
        """reference: engine.py:1563 (dist_saver). Single logical
        checkpoint: jax arrays are gathered by the save path; resharding
        on load is free because placement happens at prepare()."""
        from ...framework.io import save as fsave
        fsave(self._model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        """reference: engine.py:1646."""
        from ...framework.io import load as fload
        self._model.set_state_dict(fload(path + ".pdparams"))
        if load_optimizer and self._optimizer is not None:
            try:
                self._optimizer.set_state_dict(fload(path + ".pdopt"))
            except FileNotFoundError:
                pass
        # loaded arrays land unplaced; re-place on the mesh
        if self._prepared:
            shard_params(self._model, self._mesh)

    def cost(self, inputs_spec=None, labels_spec=None, mode=None):
        """reference: engine.py:1698 (Engine.cost). Without specs:
        coarse param count/bytes. With input specs: the completion-pass
        estimate — the model's forward is traced, the current parameter
        placements propagate through it (auto_parallel/completion.py),
        and the result prices predicted collectives, model FLOPs and
        per-device parameter memory for THIS mesh."""
        n = sum(int(np.prod(p.shape)) for p in self._model.parameters())
        by = sum(int(np.prod(p.shape)) * p._array.dtype.itemsize
                 for p in self._model.parameters())
        out = {"params": n, "bytes": by}
        if inputs_spec is None:
            return out

        self.prepare()
        from .planner import ProgramPlanner

        def _example(spec):
            if hasattr(spec, "shape"):  # InputSpec
                shape, dt = spec.shape, getattr(spec, "dtype", "float32")
            else:  # (shape, dtype) or bare shape
                shape = spec[0] if isinstance(spec[0], (list, tuple)) \
                    else spec
                dt = spec[1] if (isinstance(spec[0], (list, tuple))
                                 and len(spec) > 1) else "float32"
            shape = [8 if d in (None, -1) else int(d) for d in shape]
            return np.zeros(shape, np.dtype(getattr(dt, "name", dt)))

        def as_list(s):
            """One spec or a list of specs; a single spec may be an
            InputSpec, a (shape, dtype) pair, or a bare shape list."""
            if s is None:
                return []
            if hasattr(s, "shape"):
                return [s]
            if isinstance(s, (list, tuple)):
                if (len(s) == 2 and isinstance(s[0], (list, tuple))
                        and isinstance(s[1], str)):
                    return [s]  # (shape, dtype)
                if all(d is None or isinstance(d, int) for d in s):
                    return [s]  # bare shape
                return list(s)
            return [s]

        ins = [_example(s) for s in as_list(inputs_spec)]
        labels = [_example(s) for s in as_list(labels_spec)] \
            if labels_spec is not None else []
        params = self._params
        model, loss_fn = self._model, self._loss

        def pure(param_arrays, *data):
            saved = [p._array for p in params]
            try:
                for p, a in zip(params, param_arrays):
                    p._set_array(a)
                ins_t = [Tensor(a, stop_gradient=True)
                         for a in data[:len(ins)]]
                lab_t = [Tensor(a, stop_gradient=True)
                         for a in data[len(ins):]]
                model.eval()
                with no_grad():
                    outs = self._single(model(*ins_t))
                    loss = loss_fn(outs, *lab_t) \
                        if (loss_fn is not None and lab_t) else outs
                    if isinstance(loss, (tuple, list)):
                        loss = loss[0]
                return loss._array
            finally:
                for p, a in zip(params, saved):
                    p._set_array(a)

        def spec_of(arr):
            sh = getattr(arr, "sharding", None)
            sp = getattr(sh, "spec", None)
            return tuple(sp) if sp is not None else None

        param_arrays = [p._array for p in params]
        batch_specs = [("dp",) + (None,) * (a.ndim - 1) if a.ndim else ()
                       for a in ins + labels]
        mesh_dims = dict(self._mesh.shape)
        planner = ProgramPlanner(mesh_dims)
        score = planner.score(
            pure, (param_arrays, *ins, *labels),
            [[spec_of(a) for a in param_arrays], *batch_specs],
            params={"p": param_arrays},
            param_specs={"p": [spec_of(a) for a in param_arrays]})
        # param memory: per-leaf shard factors (the dict-of-lists form
        # above zips leaf-wise inside the planner)
        out.update({k: v for k, v in score.items() if k != "report"})
        out["reshards"] = [repr(r) for r in score["report"].reshards]
        return out
