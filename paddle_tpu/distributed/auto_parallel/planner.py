"""Sharding planner: cost-model-driven PartitionSpec selection.

Reference analog: python/paddle/distributed/auto_parallel/planner_v2.py
(Planner: completion + rule-based dist-attr search over the cost model)
and tuner/ (profile-guided search). The reference searches per-op
dist_attrs for a program graph; on the TPU stack the searchable object
is simpler — a PartitionSpec per parameter — because XLA/GSPMD derives
every activation sharding and inserts collectives once the parameter
and batch placements are fixed.

Per leaf the planner scores each candidate spec (replicated, or one
mesh axis on one divisible dim, or stacked combinations on distinct
dims) with:

    cost = per_device_bytes                       (memory pressure)
         + all_gather_cost(gathered_bytes)        (weights move per step
           when sharded on a data axis — the ZeRO-3 tradeoff)
         + all_reduce_cost(grad_bytes over data axes the weight is NOT
           sharded on)                            (grad sync)

weighted by ``mem_weight`` (HBM scarcity knob). The plan is
deterministic, explainable (``explain=True`` returns the scored
candidates), and feeds directly into NamedSharding/shard_tensor.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from .cost_model import (CommContext, all_gather_cost, all_reduce_cost,
                         reduce_scatter_cost)

__all__ = ["ShardingPlanner", "ProgramPlanner", "plan_mesh"]


class ShardingPlanner:
    def __init__(self, mesh, data_axes: Sequence[str] = ("dp",),
                 ctx: Optional[CommContext] = None,
                 mem_weight: float = 1.0, dtype_bytes: int = 4,
                 max_axes_per_tensor: int = 2):
        self.mesh = mesh
        self.axis_sizes = dict(zip(mesh.axis_names,
                                   np.asarray(mesh.devices).shape))
        self.data_axes = [a for a in data_axes if a in self.axis_sizes]
        self.ctx = ctx or CommContext()
        self.mem_weight = mem_weight
        self.dtype_bytes = dtype_bytes
        self.max_axes = max_axes_per_tensor

    # -- candidate generation ------------------------------------------
    def _candidates(self, shape) -> List[Tuple]:
        axes = [(a, n) for a, n in self.axis_sizes.items() if n > 1]
        cands = [tuple([None] * len(shape))]
        for r in range(1, self.max_axes + 1):
            # combinations x permutations covers every axis->dim
            # assignment exactly once (permutations x permutations would
            # generate each r! times)
            for axis_combo in itertools.combinations(axes, r):
                for dim_combo in itertools.permutations(
                        range(len(shape)), r):
                    ok = all(shape[d] % n == 0 and shape[d] >= n
                             for (_, n), d in zip(axis_combo, dim_combo))
                    if not ok:
                        continue
                    spec = [None] * len(shape)
                    for (a, _), d in zip(axis_combo, dim_combo):
                        spec[d] = a
                    cands.append(tuple(spec))
        return list(dict.fromkeys(cands))

    # -- scoring -------------------------------------------------------
    def _score(self, shape, spec) -> float:
        total = int(np.prod(shape)) * self.dtype_bytes if shape else \
            self.dtype_bytes
        shard_factor = 1
        used_axes = [a for a in spec if a is not None]
        for a in used_axes:
            shard_factor *= self.axis_sizes[a]
        per_dev = total / shard_factor
        cost = self.mem_weight * per_dev / self.ctx.bw  # bytes→us scale
        # every sharded axis implies at least one ICI hop of latency at a
        # use site (a gather, a partial-sum, a halo); this keeps the
        # planner from sharding tiny tensors for an epsilon of memory
        cost += self.ctx.lat * len(used_axes)

        # sharding a weight over a DATA axis = ZeRO-3: all-gathered twice
        # per step (forward + backward recompute of the gather) and its
        # gradient reduce-scattered — 3 payload units vs all-reduce's 2,
        # which is exactly why ZeRO-3 only wins under memory pressure.
        # The payload each dp-group member moves is the tensor AFTER any
        # model-axis sharding (a dp+mp hybrid gathers 1/mp of the rows).
        nondata = 1
        for a in used_axes:
            if a not in self.data_axes:
                nondata *= self.axis_sizes[a]
        payload = total / nondata
        for a in used_axes:
            if a in self.data_axes:
                n = self.axis_sizes[a]
                cost += 2 * all_gather_cost(payload, n, self.ctx, a)
                cost += reduce_scatter_cost(payload, n, self.ctx, a)
        # grad sync: all-reduce over every data axis the weight is not
        # itself sharded on
        for a in self.data_axes:
            if a not in used_axes:
                cost += all_reduce_cost(per_dev, self.axis_sizes[a],
                                        self.ctx, a)
        return cost

    # -- public --------------------------------------------------------
    def plan_leaf(self, shape, explain: bool = False):
        cands = self._candidates(tuple(shape))
        scored = sorted(((self._score(shape, c), c) for c in cands),
                        key=lambda t: t[0])
        best = P(*scored[0][1]) if shape else P()
        if explain:
            return best, [(c, s) for s, c in scored]
        return best

    def plan(self, tree) -> Any:
        """Pytree of shapes (tuples/lists or arrays with .shape) →
        pytree of PartitionSpecs."""
        import jax

        def leaf_shape(x):
            if hasattr(x, "shape"):
                return tuple(x.shape)
            return tuple(x)

        return jax.tree_util.tree_map(
            lambda x: self.plan_leaf(leaf_shape(x)), tree,
            is_leaf=lambda x: hasattr(x, "shape") or (
                isinstance(x, (tuple, list))
                and all(isinstance(i, int) for i in x)))


class ProgramPlanner:
    """Whole-program candidate scoring over the completion pass.

    Reference analog: planner_v2 + tuner — rank whole dist-attr
    assignments by estimated step time, where the estimate comes from
    propagating the candidate's shardings through the ACTUAL traced
    program (completion.py) so contraction psums, activation gathers
    and gradient syncs are all priced, not just parameter placement.
    """

    def __init__(self, mesh_dims: Dict[str, int],
                 ctx: Optional[CommContext] = None,
                 peak_flops: float = 459e12, dtype_bytes: int = 4,
                 data_axes: Sequence[str] = ("dp",)):
        self.mesh_dims = dict(mesh_dims)
        self.ctx = ctx or CommContext()
        self.peak = peak_flops
        self.dtype_bytes = dtype_bytes
        self.data_axes = list(data_axes)

    def _param_mem_and_sync(self, params, specs):
        import jax
        flat_p = jax.tree_util.tree_leaves(params)
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: x is None or isinstance(x, (tuple, P)))
        mem = 0.0
        sync_us = 0.0
        for a, s in zip(flat_p, flat_s):
            nb = int(np.prod(np.shape(a))) * self.dtype_bytes
            entries = tuple(s) if s is not None else ()
            factor = 1
            for e in entries:
                if e is not None:
                    factor *= self.mesh_dims.get(e, 1)
            mem += nb / factor
            # gradient sync over every data axis the param is not
            # sharded on (GSPMD psums grads across the batch axes)
            for ax in self.data_axes:
                n = self.mesh_dims.get(ax, 1)
                if n > 1 and ax not in entries:
                    sync_us += all_reduce_cost(nb / factor, n, self.ctx,
                                               ax)
        return mem, sync_us

    def score(self, fn, example_args, in_specs, params=None,
              param_specs=None):
        """-> dict(total_us, comm_us, compute_us, grad_sync_us,
        param_bytes_per_device, report)."""
        from .completion import propagate_sharding

        report = propagate_sharding(fn, example_args, in_specs,
                                    self.mesh_dims, self.ctx)
        # per-device compute: total model FLOPs spread over the mesh
        # (assumes the matmuls shard over every axis — the estimate the
        # reference cost model makes too; replicated compute shows up as
        # an underestimate, acceptable for RANKING candidates)
        n_dev = max(1, int(np.prod(list(self.mesh_dims.values() or [1]))))
        compute_us = report.flops / (self.peak * n_dev) * 1e6
        mem, sync_us = 0.0, 0.0
        if params is not None and param_specs is not None:
            mem, sync_us = self._param_mem_and_sync(params, param_specs)
        return {
            "total_us": report.comm_us + compute_us + sync_us,
            "comm_us": report.comm_us,
            "compute_us": compute_us,
            "grad_sync_us": sync_us,
            "param_bytes_per_device": mem,
            "report": report,
        }

    def rank(self, candidates):
        """candidates: list of (label, score_dict) -> sorted by
        total_us ascending."""
        return sorted(candidates, key=lambda c: c[1]["total_us"])


def plan_mesh(fn, make_args_and_specs, n_devices: int,
              axes: Sequence[str] = ("dp", "mp"),
              ctx: Optional[CommContext] = None,
              peak_flops: float = 459e12,
              hbm_budget_bytes: Optional[float] = None):
    """Search device-count factorizations over the named axes.

    make_args_and_specs(mesh_dims) -> (example_args, in_specs, params,
    param_specs) for that topology. Returns the ranked list of
    (mesh_dims, score) with infeasible candidates (over HBM budget)
    dropped — the tuner's search loop with the completion-pass cost
    model as the objective.
    """
    cands = []
    for a in range(1, n_devices + 1):
        if n_devices % a:
            continue  # every divisor pair, not just powers of two
        b = n_devices // a
        mesh_dims = {axes[0]: a, axes[1]: b}
        args, in_specs, params, param_specs = make_args_and_specs(
            mesh_dims)
        planner = ProgramPlanner(mesh_dims, ctx, peak_flops,
                                 data_axes=(axes[0],))
        s = planner.score(fn, args, in_specs, params, param_specs)
        if hbm_budget_bytes is not None and \
                s["param_bytes_per_device"] > hbm_budget_bytes:
            continue
        cands.append((mesh_dims, s))
    return sorted(cands, key=lambda c: c[1]["total_us"])
