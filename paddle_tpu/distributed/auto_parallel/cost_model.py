"""Collective + memory cost model for sharding decisions.

Reference analog: python/paddle/distributed/auto_parallel/cost/
(comm_op_cost.py AllreduceSumOpCost/AllgatherOpCost with alpha-beta
ring-time formulas, cost_model.py) feeding planner_v2/tuner.

TPU-native: the alpha-beta constants model ICI, not NVLink/IB. The ring
formulas are topology-independent in shape — what changes is the link
bandwidth and that TPU meshes give each axis its own dedicated ICI
links (so per-axis costs add, they don't contend). Bandwidth default is
v5p-class ICI (~100 GB/s effective per link direction); override for
other generations. All costs are in microseconds so they compose with
the reference's convention.
"""
from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["CommContext", "all_reduce_cost", "all_gather_cost",
           "reduce_scatter_cost", "all_to_all_cost", "p2p_cost"]


class CommContext:
    """Per-axis link model: bandwidth (bytes/us) + latency (us/hop)."""

    def __init__(self, ici_bandwidth_gbps: float = 100.0,
                 latency_us: float = 1.0,
                 dcn_bandwidth_gbps: float = 12.5,
                 dcn_axes: Sequence[str] = ()):
        self.bw = ici_bandwidth_gbps * 1e9 / 1e6  # bytes per microsecond
        self.dcn_bw = dcn_bandwidth_gbps * 1e9 / 1e6
        self.lat = latency_us
        self.dcn_axes = set(dcn_axes)

    def axis_bw(self, axis_name: Optional[str]) -> float:
        if axis_name in self.dcn_axes:
            return self.dcn_bw
        return self.bw


def _ring(nbytes: int, n: int, ctx: CommContext, axis=None,
          factor: float = 1.0) -> float:
    """alpha-beta ring time: (n-1) latency hops + (n-1)/n of the payload
    over the link, scaled by `factor` (1 for gather/scatter, 2 for
    all-reduce = reduce-scatter + all-gather)."""
    if n <= 1:
        return 0.0
    bw = ctx.axis_bw(axis)
    return factor * ((n - 1) * ctx.lat + (n - 1) / n * nbytes / bw)


def all_reduce_cost(nbytes, n, ctx=None, axis=None):
    return _ring(nbytes, n, ctx or CommContext(), axis, factor=2.0)


def all_gather_cost(nbytes, n, ctx=None, axis=None):
    return _ring(nbytes, n, ctx or CommContext(), axis, factor=1.0)


def reduce_scatter_cost(nbytes, n, ctx=None, axis=None):
    return _ring(nbytes, n, ctx or CommContext(), axis, factor=1.0)


def all_to_all_cost(nbytes, n, ctx=None, axis=None):
    if n <= 1:
        return 0.0
    ctx = ctx or CommContext()
    return (n - 1) * ctx.lat + (n - 1) / n * nbytes / ctx.axis_bw(axis)


def p2p_cost(nbytes, ctx=None, axis=None):
    ctx = ctx or CommContext()
    return ctx.lat + nbytes / ctx.axis_bw(axis)
