"""Validate completion.py's reshard predictions against GSPMD ground truth.

Reference analog: the reference trusts its Completer/Resharder passes
because they ARE the partitioner — what they decide is what runs
(auto_parallel/completion.py:928, reshard.py). Here XLA's GSPMD is the
partitioner, so the prediction layer (completion.propagate_sharding)
needs an independent check: compile the same program with the same
input shardings and compare the collectives XLA actually emitted
(kind, mesh axis, payload bytes) against the PropagationReport.

The comparison contract:
- counts per collective kind must match exactly (an all-reduce XLA
  combined from k logical reductions counts as its k operands);
- total payload bytes per kind must agree within ``rtol``;
- every predicted mesh axis must appear in the HLO's replica groups
  (axis attribution), and vice versa.

Payload convention (both sides): the PER-DEVICE operand bytes of the
collective — for an all-gather that is the local shard being gathered,
for an all-reduce the local partial-sum buffer. This is what the ring
cost model's alpha-beta time actually moves over a link.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["HloCollective", "hlo_collectives", "compare_report",
           "validate_propagation"]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# one collective-instruction definition line in optimized HLO, e.g.
#   %all-reduce.3 = f32[4,64]{1,0} all-reduce(f32[4,64]{1,0} %p),
#       channel_id=1, replica_groups={{0,4},{1,5}}, ...
# async pairs appear as all-reduce-start / all-reduce-done: count the
# -start (it carries operands + groups), skip the -done.
_COLL_RE = re.compile(
    r"=\s+(?P<result>\([^)]*\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?P<async>-start|-done)?\s*"
    r"\((?P<operands>.*?)\)(?P<attrs>.*)$")

_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")


@dataclass
class HloCollective:
    kind: str                      # all_reduce / all_gather / ...
    nbytes: int                    # summed per-device operand bytes
    n_logical: int                 # operand count (combiner-merged ops)
    axis: Optional[str]            # mesh axis inferred from groups
    groups: Tuple[Tuple[int, ...], ...]

    def __repr__(self):
        return (f"HloCollective({self.kind} over {self.axis}, "
                f"{self.nbytes} B, x{self.n_logical})")


def _shape_bytes(text: str) -> int:
    """Sum the bytes of every dtype[shape] occurrence in ``text``."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _parse_groups(attrs: str) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """replica_groups in either explicit {{0,1},{2,3}} or iota
    [g,s]<=[dims]T(perm) form -> tuple of device-id tuples."""
    m = re.search(r"replica_groups=\{(\{[\d,{}\s]*\})\}", attrs)
    if m:
        groups = re.findall(r"\{([\d,\s]*)\}", m.group(1))
        return tuple(tuple(int(x) for x in g.replace(" ", "").split(",")
                           if x) for g in groups)
    m = re.search(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?",
        attrs)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            ids = ids.transpose(perm)
        return tuple(tuple(int(x) for x in row)
                     for row in ids.reshape(g, s))
    return None


def _axis_groups(mesh) -> Dict[str, frozenset]:
    """mesh axis name -> the set of device-id groups a collective over
    exactly that axis uses (each group = ids varying along the axis
    with every other axis coordinate fixed)."""
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    out = {}
    for i, name in enumerate(mesh.axis_names):
        moved = np.moveaxis(ids, i, -1).reshape(-1, ids.shape[i])
        out[name] = frozenset(frozenset(int(x) for x in row)
                              for row in moved)
    return out


def _infer_axis(groups, axis_map) -> Optional[str]:
    if groups is None:
        return None
    gs = frozenset(frozenset(g) for g in groups)
    for name, expect in axis_map.items():
        if gs == expect:
            return name
    # a collective over a product of axes (or a sub-mesh) matches none
    return None


def hlo_collectives(fn, example_args, in_specs, mesh,
                    out_specs=None) -> List[HloCollective]:
    """Compile ``fn`` under GSPMD with the given input shardings and
    return the collectives present in the optimized HLO."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def to_sharding(spec):
        if isinstance(spec, NamedSharding):
            return spec
        if spec is None:
            return NamedSharding(mesh, PartitionSpec())
        if isinstance(spec, PartitionSpec):
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, PartitionSpec(*spec))

    flat_specs = jax.tree_util.tree_leaves(
        in_specs, is_leaf=lambda x: x is None or isinstance(
            x, (tuple, PartitionSpec, NamedSharding)))
    flat_args, treedef = jax.tree_util.tree_flatten(example_args)
    if len(flat_specs) != len(flat_args):
        raise ValueError(f"in_specs ({len(flat_specs)} leaves) does not "
                         f"match example_args ({len(flat_args)})")
    in_sh = jax.tree_util.tree_unflatten(
        treedef, [to_sharding(s) for s in flat_specs])
    kw = {}
    if out_specs is not None:
        if isinstance(out_specs, list):  # several outputs -> fn returns
            # a tuple; shardings must mirror that container type
            kw["out_shardings"] = tuple(to_sharding(s) for s in out_specs)
        else:
            kw["out_shardings"] = to_sharding(out_specs)
    compiled = jax.jit(fn, in_shardings=(in_sh if isinstance(
        in_sh, tuple) else (in_sh,)), **kw).lower(*example_args).compile()
    txt = compiled.as_text()

    axis_map = _axis_groups(mesh)
    out: List[HloCollective] = []
    for line in txt.splitlines():
        m = _COLL_RE.search(line)
        if not m or m.group("async") == "-done":
            continue
        # operands are bare %refs in optimized HLO — bytes come from the
        # RESULT shape (a tuple when the all-reduce combiner merged
        # several logical reductions; each element is one logical op)
        result = m.group("result")
        shapes = _SHAPE_RE.findall(result)
        if m.group("async") == "-start" and len(shapes) % 2 == 0 \
                and m.group("op") != "all-reduce":
            # async gather/permute/a2a -start results echo the operands:
            # ((op...), (result...)) — k logical ops with 2k shapes;
            # keep the result half only (counts AND bytes)
            shapes = shapes[len(shapes) // 2:]
            result = " ".join(f"{dt}[{dims}]" for dt, dims in shapes)
        nbytes = _shape_bytes(result)
        n_logical = max(1, len(shapes))
        groups = _parse_groups(m.group("attrs"))
        n_group = len(groups[0]) if groups else 1
        kind = m.group("op").replace("-", "_")
        if kind == "all_gather" and n_group:
            # result is the gathered buffer; the per-device operand
            # shard (the payload convention) is 1/n of it
            nbytes //= n_group
        groups = groups or ()
        out.append(HloCollective(
            kind=kind, nbytes=nbytes, n_logical=n_logical,
            axis=_infer_axis(groups, axis_map),
            groups=groups))
    return out


def _fold_rs_ag(items: Sequence[HloCollective],
                predicted_kinds) -> List[HloCollective]:
    """Fold XLA's reduce-scatter(+matching all-gather) rewrite of a
    logical all-reduce back into one all_reduce, so the comparison is
    in the predictor's vocabulary. Only folds when the predictor spoke
    no reduce_scatter itself; each RS consumes AT MOST ONE all-gather —
    the one whose axis and per-device operand bytes match the RS's
    scattered shard — so unrelated gathers still count (and still fail
    the comparison when the predictor missed them). The folded
    all_reduce's payload is the FULL per-device buffer (shard * group
    size), matching the predictor's convention."""
    if "reduce_scatter" in predicted_kinds or not any(
            c.kind == "reduce_scatter" for c in items):
        return list(items)
    paired = set()
    if "all_gather" not in predicted_kinds:
        gathers = [c for c in items if c.kind == "all_gather"]
        for c in items:
            if c.kind != "reduce_scatter":
                continue
            mate = next(
                (g for g in gathers if id(g) not in paired
                 and g.axis == c.axis and g.nbytes == c.nbytes), None)
            if mate is not None:
                paired.add(id(mate))
    out = []
    for c in items:
        if c.kind == "reduce_scatter":
            n = len(c.groups[0]) if c.groups else 1
            out.append(HloCollective(
                kind="all_reduce", nbytes=c.nbytes * n,
                n_logical=c.n_logical, axis=c.axis, groups=c.groups))
        elif id(c) in paired:
            continue  # the gather half of the rewrite
        else:
            out.append(c)
    return out


def compare_report(report, hlo: Sequence[HloCollective],
                   rtol: float = 0.3) -> Dict:
    """Compare a PropagationReport against parsed HLO collectives.

    Returns {"ok": bool, "mismatches": [...], "predicted": ..,
    "actual": ..}. reduce-scatter+all-gather pairs XLA rewrites from a
    logical all-reduce are folded back into one all_reduce (see
    _fold_rs_ag).
    """
    def bucket_pred():
        counts: Dict[str, int] = {}
        bytes_: Dict[str, int] = {}
        axes: Dict[str, set] = {}
        for r in report.reshards:
            counts[r.kind] = counts.get(r.kind, 0) + 1
            bytes_[r.kind] = bytes_.get(r.kind, 0) + r.nbytes
            axes.setdefault(r.kind, set()).add(r.axis)
        return counts, bytes_, axes

    def bucket_hlo(items):
        counts: Dict[str, int] = {}
        bytes_: Dict[str, int] = {}
        axes: Dict[str, set] = {}
        for c in items:
            counts[c.kind] = counts.get(c.kind, 0) + c.n_logical
            bytes_[c.kind] = bytes_.get(c.kind, 0) + c.nbytes
            axes.setdefault(c.kind, set()).add(c.axis)
        return counts, bytes_, axes

    pc, pb, pa = bucket_pred()
    ac, ab, aa = bucket_hlo(_fold_rs_ag(hlo, set(pc)))

    mismatches = []
    for kind in sorted(set(pc) | set(ac)):
        if pc.get(kind, 0) != ac.get(kind, 0):
            mismatches.append(
                f"{kind}: predicted {pc.get(kind, 0)} collectives, "
                f"HLO has {ac.get(kind, 0)}")
            continue
        want, got = pb.get(kind, 0), ab.get(kind, 0)
        if want and got and abs(want - got) > rtol * max(want, got):
            mismatches.append(
                f"{kind}: predicted {want} B, HLO moves {got} B "
                f"(>{rtol:.0%} apart)")
        pred_axes = {a for a in pa.get(kind, set()) if a is not None}
        hlo_axes = {a for a in aa.get(kind, set()) if a is not None}
        if pred_axes and hlo_axes and pred_axes != hlo_axes:
            mismatches.append(
                f"{kind}: predicted axes {sorted(pred_axes)}, "
                f"HLO groups map to {sorted(hlo_axes)}")
    return {
        "ok": not mismatches, "mismatches": mismatches,
        "predicted": {"counts": pc, "bytes": pb,
                      "axes": {k: sorted(filter(None, v))
                               for k, v in pa.items()}},
        "actual": {"counts": ac, "bytes": ab,
                   "axes": {k: sorted(filter(None, v))
                            for k, v in aa.items()}},
    }


def validate_propagation(fn, example_args, in_specs, mesh,
                         rtol: float = 0.3, use_out_specs: bool = True
                         ) -> Dict:
    """Run the predictor AND the compiler on the same sharded program
    and compare. ``use_out_specs`` pins XLA's output shardings to the
    predictor's inferred ones so the two sides answer the same
    question (otherwise XLA is free to pick a different output layout
    and the reshard sets legitimately differ)."""
    from .completion import propagate_sharding

    mesh_dims = dict(zip(mesh.axis_names,
                         np.array(mesh.devices).shape))
    report = propagate_sharding(fn, example_args, in_specs, mesh_dims)
    out_specs = None
    if use_out_specs:
        outs = report.out_specs
        # single output: the bare spec tuple; several: a LIST of spec
        # tuples (a list so tree_map's tuple is_leaf hits each spec,
        # not the container)
        out_specs = outs[0] if len(outs) == 1 else list(outs)
    hlo = hlo_collectives(fn, example_args, in_specs, mesh,
                          out_specs=out_specs)
    result = compare_report(report, hlo, rtol=rtol)
    result["report"] = report
    result["hlo"] = hlo
    return result
