"""Sharding completion + reshard prediction over a traced jaxpr.

Reference analog: auto_parallel/completion.py:928 (the Completer —
propagates ProcessMesh + dims_mapping annotations op by op over the
serial ProgramDesc), partitioner.py and reshard.py (insert collectives
where producer/consumer dist attrs disagree).

TPU-native: XLA's GSPMD partitioner does the actual propagate/
partition/reshard at compile time — what the framework still needs is
the *reasoning* layer the reference builds these passes for: given
parameter/input PartitionSpecs, walk the traced jaxpr with
per-primitive SPMD rules, infer every intermediate's sharding, and
record each point where GSPMD will have to insert a collective (the
reshard set) with its byte volume and estimated time. That feeds the
planner with per-candidate cost estimates that reflect the PROGRAM,
not just parameter shapes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .cost_model import (CommContext, all_gather_cost, all_reduce_cost)

__all__ = ["Reshard", "PropagationReport", "propagate_sharding"]

Spec = Tuple[Optional[str], ...]  # one mesh-axis name (or None) per dim


def _norm_spec(spec, ndim) -> Spec:
    """PartitionSpec / tuple / None -> per-dim tuple padded to ndim."""
    if spec is None:
        return (None,) * ndim
    entries = tuple(spec)
    out = []
    for e in entries[:ndim]:
        if isinstance(e, (tuple, list)):  # multi-axis dim: keep first
            out.append(e[0] if e else None)
        else:
            out.append(e)
    out.extend([None] * (ndim - len(out)))
    return tuple(out)


@dataclass
class Reshard:
    """One predicted GSPMD collective insertion."""
    prim: str
    kind: str          # all_reduce / all_gather / replicate
    axis: Optional[str]
    nbytes: int
    cost_us: float

    def __repr__(self):
        return (f"Reshard({self.prim}: {self.kind} over {self.axis}, "
                f"{self.nbytes / 1e6:.2f} MB, {self.cost_us:.1f} us)")


@dataclass
class PropagationReport:
    out_specs: List[Spec] = field(default_factory=list)
    reshards: List[Reshard] = field(default_factory=list)
    flops: float = 0.0

    @property
    def comm_us(self) -> float:
        return sum(r.cost_us for r in self.reshards)

    def comm_bytes(self, axis=None) -> int:
        return sum(r.nbytes for r in self.reshards
                   if axis is None or r.axis == axis)


def _nbytes(aval) -> int:
    return int(np.prod(aval.shape)) * aval.dtype.itemsize if aval.shape \
        else aval.dtype.itemsize


class _Propagator:
    def __init__(self, mesh_dims: Dict[str, int], ctx: CommContext):
        self.mesh = dict(mesh_dims)
        self.ctx = ctx
        self.report = PropagationReport()
        self._mute = 0  # >0 during fixpoint probing runs (no recording)

    # -- helpers ------------------------------------------------------------
    def _axis_n(self, axis) -> int:
        return int(self.mesh.get(axis, 1))

    def _record(self, prim, kind, axis, nbytes):
        if self._mute:
            return
        n = self._axis_n(axis)
        if n <= 1 or nbytes == 0:
            return
        if kind == "all_reduce":
            cost = all_reduce_cost(nbytes, n, self.ctx, axis)
        else:
            cost = all_gather_cost(nbytes, n, self.ctx, axis)
        self.report.reshards.append(
            Reshard(prim, kind, axis, int(nbytes), float(cost)))

    def _local_bytes(self, aval, spec: Spec) -> int:
        """Per-device shard bytes of a value under ``spec`` — the
        payload convention shared with validate.hlo_collectives (what
        one device actually puts on the wire)."""
        n = _nbytes(aval)
        for ax in spec:
            if ax is not None:
                n //= self._axis_n(ax)
        return n

    def _record_gathers(self, prim, aval, full_spec: Spec, gather_axes):
        """Record sequential all-gathers of ``gather_axes``: each gather
        grows the per-device buffer, so later gathers move more bytes
        (a 2-axis replicate is local + local*n1, not 2x local)."""
        local = self._local_bytes(aval, full_spec)
        for ax in gather_axes:
            if ax is not None:
                self._record(prim, "all_gather", ax, local)
                local *= self._axis_n(ax)

    def _gather_to_replicated(self, prim, spec: Spec, aval) -> Spec:
        """Record the all-gathers needed to fully replicate a value."""
        self._record_gathers(prim, aval, spec,
                             [ax for ax in spec if ax is not None])
        return (None,) * len(spec)

    # -- per-primitive rules ------------------------------------------------
    def _rule_elementwise(self, prim, in_specs, in_avals, out_avals):
        """Same-shape operands: merge specs dim-wise; a conflict means
        one operand reshards (gather the smaller)."""
        out_ndim = len(out_avals[0].shape)
        out_shape = tuple(out_avals[0].shape)
        merged: List[Optional[str]] = [None] * out_ndim
        for d in range(out_ndim):
            # a size-1 operand dim broadcasts: it is replicated along d
            # and contributes no sharding (softmax's x - max(keepdims))
            axes = {s[d] for s, a in zip(in_specs, in_avals)
                    if len(a.shape) == out_ndim and s[d] is not None
                    and a.shape[d] == out_shape[d]}
            if len(axes) == 1:
                merged[d] = axes.pop()
            elif len(axes) > 1:
                # conflict: keep the axis backed by the most operand
                # bytes (gathering the smaller side moves less data —
                # GSPMD's merge heuristic), gather the rest
                vol: Dict[str, int] = {}
                for s, a in zip(in_specs, in_avals):
                    if s[d] is not None:
                        vol[s[d]] = vol.get(s[d], 0) \
                            + self._local_bytes(a, s)
                keep = max(sorted(vol), key=lambda ax: vol[ax])
                merged[d] = keep
                for s, a in zip(in_specs, in_avals):
                    if s[d] is not None and s[d] != keep:
                        self._record(prim, "all_gather", s[d],
                                     self._local_bytes(a, s))
        return [tuple(merged)] * len(out_avals)

    def _rule_dot_general(self, prim, params, in_specs, in_avals,
                          out_avals):
        ((lc, rc), (lb, rb)) = params["dimension_numbers"]
        ls, rs = in_specs
        la, ra = in_avals
        out_ndim = len(out_avals[0].shape)
        out: List[Optional[str]] = [None] * out_ndim
        # output layout: batch dims, then left free, then right free
        pos = 0
        for dl, dr in zip(lb, rb):
            out[pos] = ls[dl] if ls[dl] is not None else rs[dr]
            pos += 1
        for d in range(len(la.shape)):
            if d not in lc and d not in lb:
                out[pos] = ls[d]
                pos += 1
        for d in range(len(ra.shape)):
            if d not in rc and d not in rb:
                out[pos] = rs[d]
                pos += 1
        # contracting dims: matching shard -> partial result (psum of
        # the per-device OUTPUT shard — free dims may themselves be
        # sharded, e.g. a dp batch dim, shrinking the psum payload);
        # one-sided shard -> gather that operand's local shard
        for dl, dr in zip(lc, rc):
            al, ar = ls[dl], rs[dr]
            if al is not None and al == ar:
                self._record(prim, "all_reduce", al,
                             self._local_bytes(out_avals[0], tuple(out)))
            elif al is not None and ar is None:
                self._record(prim, "all_gather", al,
                             self._local_bytes(la, ls))
            elif ar is not None and al is None:
                self._record(prim, "all_gather", ar,
                             self._local_bytes(ra, rs))
            elif al is not None and ar is not None:
                self._record(prim, "all_gather", al,
                             self._local_bytes(la, ls))
                self._record(prim, "all_gather", ar,
                             self._local_bytes(ra, rs))
        # model FLOPs: 2 * prod(out) * prod(contract)
        contract = int(np.prod([la.shape[d] for d in lc])) if lc else 1
        self.report.flops += 2.0 * float(np.prod(out_avals[0].shape)) \
            * contract
        return [tuple(out)]

    def _rule_reduce(self, prim, params, in_specs, in_avals, out_avals):
        axes = params.get("axes", ())
        spec = in_specs[0]
        out = tuple(s for d, s in enumerate(spec) if d not in axes)
        for d in axes:
            if spec[d] is not None:
                # any reduction over a sharded dim needs a cross-shard
                # combine of the per-device output shard (sum -> psum,
                # max -> all-reduce-max, ... — same wire cost)
                self._record(prim, "all_reduce", spec[d],
                             self._local_bytes(out_avals[0], out))
        return [out]

    def _rule_transpose(self, prim, params, in_specs, in_avals, out_avals):
        perm = params["permutation"]
        return [tuple(in_specs[0][p] for p in perm)]

    def _rule_concatenate(self, prim, params, in_specs, in_avals,
                          out_avals):
        """Concat along an unsharded dim keeps the operands' merged
        shardings (RoPE's rotate_half); an operand sharded along the
        concat dim itself reshards."""
        d_cat = int(params["dimension"])
        out_ndim = len(out_avals[0].shape)
        merged: List[Optional[str]] = [None] * out_ndim
        for d in range(out_ndim):
            if d == d_cat:
                for s, a in zip(in_specs, in_avals):
                    if s[d] is not None:
                        self._record(prim, "all_gather", s[d],
                                     self._local_bytes(a, s))
                continue
            axes = {s[d] for s in in_specs if s[d] is not None}
            if len(axes) == 1:
                merged[d] = axes.pop()
            elif len(axes) > 1:
                vol: Dict[str, int] = {}
                for s, a in zip(in_specs, in_avals):
                    if s[d] is not None:
                        vol[s[d]] = vol.get(s[d], 0) \
                            + self._local_bytes(a, s)
                keep = max(sorted(vol), key=lambda ax: vol[ax])
                merged[d] = keep
                for s, a in zip(in_specs, in_avals):
                    if s[d] is not None and s[d] != keep:
                        self._record(prim, "all_gather", s[d],
                                     self._local_bytes(a, s))
        return [tuple(merged)]

    def _rule_slice(self, prim, params, in_specs, in_avals, out_avals):
        """Slicing an UNSHARDED dim keeps every sharding (RoPE's
        half-head-dim split, qkv splits, KV-cache dynamic_slice);
        slicing into a sharded dim would need halo/gather — reshard
        that axis. Covers slice / dynamic_slice (operand spec first,
        index operands are scalars)."""
        spec, a, o = in_specs[0], in_avals[0], out_avals[0]
        out: List[Optional[str]] = [None] * len(o.shape)
        for d in range(len(a.shape)):
            if spec[d] is None:
                continue
            if a.shape[d] == o.shape[d]:
                out[d] = spec[d]  # full extent: sharding survives
            else:
                self._record(prim, "all_gather", spec[d],
                             self._local_bytes(a, spec))
        return [tuple(out)]

    def _rule_dus(self, prim, params, in_specs, in_avals, out_avals):
        """dynamic_update_slice (KV-cache writes): the operand's spec
        survives on dims the update spans fully or that are unsharded;
        updating into a sharded dim reshards the update."""
        spec, upd_spec = in_specs[0], in_specs[1]
        a, u = in_avals[0], in_avals[1]
        out: List[Optional[str]] = list(spec)
        for d in range(len(a.shape)):
            if spec[d] is not None and a.shape[d] != u.shape[d]:
                # partial write into a sharded dim: the update must
                # reach the owning shard
                self._record(prim, "all_gather", spec[d],
                             self._local_bytes(u, upd_spec))
            elif upd_spec[d] is not None and upd_spec[d] != spec[d]:
                # update sharded where the operand's layout differs:
                # GSPMD reshards the update to the operand's layout
                self._record(prim, "all_gather", upd_spec[d],
                             self._local_bytes(u, upd_spec))
        return [tuple(out)]

    def _rule_pad(self, prim, params, in_specs, in_avals, out_avals):
        """Padding an unsharded dim keeps shardings; padding a sharded
        dim changes its extent non-uniformly across shards — reshard."""
        cfg = params.get("padding_config", ())
        spec, a = in_specs[0], in_avals[0]
        out: List[Optional[str]] = [None] * len(out_avals[0].shape)
        for d in range(len(a.shape)):
            lo, hi, interior = cfg[d] if d < len(cfg) else (0, 0, 0)
            if spec[d] is None:
                continue
            if lo == 0 and hi == 0 and interior == 0:
                out[d] = spec[d]
            else:
                self._record(prim, "all_gather", spec[d],
                             self._local_bytes(a, spec))
        return [tuple(out)]

    def _rule_gather(self, prim, params, in_specs, in_avals, out_avals):
        """Embedding-style and batch-aligned gathers propagate without
        collectives under GSPMD:

        - fully replicated operand (embed[ids]): the output's batch
          dims take the indices' shardings, offset dims replicate;
        - operand sharded ONLY on batching dims whose paired indices
          dim carries the same axis (take_along_axis on a dp batch):
          same propagation, shard included.

        Anything else (operand sharded on a gathered dim) falls back to
        the conservative gather-to-replicated."""
        dn = params["dimension_numbers"]
        op_spec, idx_spec = in_specs[0], in_specs[1]
        op_a, idx_a = in_avals[0], in_avals[1]
        obd = tuple(getattr(dn, "operand_batching_dims", ()) or ())
        sbd = tuple(getattr(dn, "start_indices_batching_dims", ()) or ())
        aligned = True
        for d, ax in enumerate(op_spec):
            if ax is None:
                continue
            if d in obd and idx_spec[sbd[obd.index(d)]] == ax:
                continue
            aligned = False
            break
        if not aligned:
            for s, a in zip(in_specs, in_avals):
                if any(x is not None for x in s):
                    self._gather_to_replicated(prim, s, a)
            return [(None,) * len(o.shape) for o in out_avals]
        # output layout: non-offset dims mirror the indices' batch dims
        # (all indices dims except the trailing index-vector dim), in
        # order; offset dims are slice extents (replicated)
        offset = set(dn.offset_dims)
        idx_batch = [idx_spec[d] for d in range(len(idx_a.shape) - 1)]
        o = out_avals[0]
        out_spec: List[Optional[str]] = [None] * len(o.shape)
        it = iter(idx_batch)
        for d in range(len(o.shape)):
            if d not in offset:
                out_spec[d] = next(it, None)
        return [tuple(out_spec)]

    def _rule_reshape(self, prim, params, in_specs, in_avals, out_avals):
        """Factor the reshape into groups of input/output dims with
        equal products (the GSPMD propagation view of reshape):

        - 1->1 group: the sharding carries over;
        - 1->k split: the sharding lands on the FIRST sub-dim when the
          axis size divides it (e.g. [B,S,H] -> [B,S,heads,hd] keeps an
          'mp' shard of H on heads — the Megatron head split);
        - k->1 merge: a shard of the group's leading dim carries to the
          merged dim (contiguous blocks); shards of later dims cannot
          be represented and reshard;
        - general k->k: conservative gather.
        """
        spec, a, o = in_specs[0], in_avals[0], out_avals[0]
        ishape, oshape = list(a.shape), list(o.shape)
        out: List[Optional[str]] = [None] * len(oshape)
        lost: List[str] = []
        # size-1 dims carry no data and would mis-anchor the grouping
        # ([1,B,H]->[B,H], [B,H]->[B,1,H] must keep shards with no
        # collective): factor them out, group only the non-1 dims
        ii = [d for d in range(len(ishape)) if ishape[d] != 1]
        oo = [d for d in range(len(oshape)) if oshape[d] != 1]
        i = j = 0
        while i < len(ii) and j < len(oo):
            i2, j2 = i + 1, j + 1
            pi, pj = ishape[ii[i]], oshape[oo[j]]
            while pi != pj and (i2 < len(ii) or j2 < len(oo)):
                if pi < pj and i2 < len(ii):
                    pi *= ishape[ii[i2]]
                    i2 += 1
                elif j2 < len(oo):
                    pj *= oshape[oo[j2]]
                    j2 += 1
                else:
                    break
            n_in, n_out = i2 - i, j2 - j
            if n_in == 1 and n_out == 1:
                out[oo[j]] = spec[ii[i]]
            elif n_in == 1 and n_out > 1:
                ax = spec[ii[i]]
                if ax is not None:
                    if oshape[oo[j]] % self._axis_n(ax) == 0:
                        out[oo[j]] = ax
                    else:
                        lost.append(ax)
            elif n_out == 1:
                ax = spec[ii[i]]
                if ax is not None and ishape[ii[i]] % self._axis_n(ax) == 0:
                    out[oo[j]] = ax
                elif ax is not None:
                    lost.append(ax)
                for d in ii[i + 1:i2]:
                    if spec[d] is not None:
                        lost.append(spec[d])
            else:  # general k->k regroup: conservative
                for d in ii[i:i2]:
                    if spec[d] is not None:
                        lost.append(spec[d])
            i, j = i2, j2
        for d in ii[i:]:  # unmatched trailing non-1 input dims
            if spec[d] is not None:
                lost.append(spec[d])
        self._record_gathers(prim, a, spec, lost)
        return [tuple(out)]

    # -- control flow -------------------------------------------------------
    def _rule_scan(self, params, in_specs, in_avals, out_avals):
        """lax.scan: propagate through the body at a FIXPOINT of the
        carry specs (probing runs muted), then one recording run whose
        per-iteration collectives get their time scaled by ``length``.
        A carry whose body output is sharded where the loop-invariant
        spec is not forces a back-edge reshard every iteration — the
        cost XLA pays as an all-gather inside the while body."""
        body = getattr(params["jaxpr"], "jaxpr", params["jaxpr"])
        nc = int(params.get("num_consts", 0))
        nk = int(params.get("num_carry", 0))
        length = int(params.get("length", 1))
        consts = list(in_specs[:nc])
        carry = [tuple(s) for s in in_specs[nc:nc + nk]]
        xs = []
        for s, a in zip(in_specs[nc + nk:], in_avals[nc + nk:]):
            if s[0] is not None:
                # xs sharded along the SCAN dim (pipeline-style layer
                # placement): every iteration fetches its slice from
                # the owning shard — one per-iteration collective of
                # the slice payload, `length` iterations
                slice_local = self._local_bytes(a, s) \
                    // max(1, int(a.shape[0]) // self._axis_n(s[0]))
                r0 = len(self.report.reshards)
                self._record("scan_xs", "all_gather", s[0], slice_local)
                for r in self.report.reshards[r0:]:
                    r.cost_us *= length
            xs.append(tuple(s[1:]))

        self._mute += 1
        try:
            for _ in range(4):
                out = self.run_sub(body, consts + carry + xs)
                merged = [tuple(a if a == b else None
                                for a, b in zip(c, o))
                          for c, o in zip(carry, out[:nk])]
                if merged == carry:
                    break
                carry = merged
        finally:
            self._mute -= 1

        n0 = len(self.report.reshards)
        out = self.run_sub(body, consts + carry + xs)
        for r in self.report.reshards[n0:]:
            r.cost_us *= length
        # back-edge reshards: body output sharded where the stable
        # carry spec is replicated
        for i in range(nk):
            for ax_o, ax_c in zip(out[i], carry[i]):
                if ax_o is not None and ax_c is None:
                    r0 = len(self.report.reshards)
                    self._record("scan_carry", "all_gather", ax_o,
                                 self._local_bytes(out_avals[i], out[i]))
                    for r in self.report.reshards[r0:]:
                        r.cost_us *= length
        ys = [(None,) + tuple(s) for s in out[nk:]]
        return [tuple(c) for c in carry] + ys

    def _rule_while(self, params, in_specs, in_avals, out_avals):
        """lax.while_loop: like scan's fixpoint but with unknown trip
        count — per-iteration collective costs stay un-scaled (a lower
        bound), specs still converge."""
        body = getattr(params["body_jaxpr"], "jaxpr",
                       params["body_jaxpr"])
        nb = int(params.get("body_nconsts", 0))
        nc_cond = int(params.get("cond_nconsts", 0))
        consts = list(in_specs[nc_cond:nc_cond + nb])
        carry = [tuple(s) for s in in_specs[nc_cond + nb:]]
        self._mute += 1
        try:
            for _ in range(4):
                out = self.run_sub(body, consts + carry)
                merged = [tuple(a if a == b else None
                                for a, b in zip(c, o))
                          for c, o in zip(carry, out)]
                if merged == carry:
                    break
                carry = merged
        finally:
            self._mute -= 1
        self.run_sub(body, consts + carry)
        return [tuple(c) for c in carry]

    def _rule_cond(self, params, in_specs, in_avals, out_avals):
        """lax.cond/switch: every branch is materialized in the HLO, so
        each branch's reshards record; outputs take the branch meet."""
        branches = params["branches"]
        operands = list(in_specs[1:])  # invars[0] is the branch index
        outs = []
        for br in branches:
            outs.append(self.run_sub(getattr(br, "jaxpr", br), operands))
        merged = []
        for parts in zip(*outs):
            merged.append(tuple(
                a if all(a == p[i] for p in parts) else None
                for i, a in enumerate(parts[0])))
        return merged

    # -- driver -------------------------------------------------------------
    def run(self, jaxpr, in_specs: Sequence[Spec]):
        env: Dict[Any, Spec] = {}

        def read(v):
            if hasattr(v, "val"):  # Literal
                return (None,) * np.ndim(v.val)
            return env.get(v, (None,) * len(v.aval.shape))

        for var, spec in zip(jaxpr.invars, in_specs):
            env[var] = _norm_spec(spec, len(var.aval.shape))

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_specs_e = [read(v) for v in eqn.invars]
            in_avals = [v.aval if not hasattr(v, "val")
                        else np.asarray(v.val) for v in eqn.invars]
            out_avals = [v.aval for v in eqn.outvars]

            if prim in ("pjit", "jit", "closed_call", "custom_jvp_call",
                        "custom_vjp_call", "remat", "remat2", "checkpoint",
                        "custom_vjp_call_jaxpr"):
                inner = eqn.params.get("jaxpr") or eqn.params.get(
                    "call_jaxpr")
                if inner is not None:
                    inner_jaxpr = getattr(inner, "jaxpr", inner)
                    sub_out = self.run_sub(inner_jaxpr, in_specs_e)
                    for v, s in zip(eqn.outvars, sub_out):
                        env[v] = s
                    continue
            rule_out = self._dispatch(prim, eqn.params, in_specs_e,
                                      in_avals, out_avals)
            for v, s in zip(eqn.outvars, rule_out):
                env[v] = _norm_spec(s, len(v.aval.shape))
        return [read(v) for v in jaxpr.outvars]

    def run_sub(self, jaxpr, in_specs):
        return self.run(jaxpr, in_specs)

    def _dispatch(self, prim, params, in_specs, in_avals, out_avals):
        if prim == "scan":
            return self._rule_scan(params, in_specs, in_avals, out_avals)
        if prim == "while":
            return self._rule_while(params, in_specs, in_avals,
                                    out_avals)
        if prim == "cond":
            return self._rule_cond(params, in_specs, in_avals, out_avals)
        if prim == "dot_general":
            return self._rule_dot_general(prim, params, in_specs,
                                          in_avals, out_avals)
        if prim.startswith("reduce_"):
            return self._rule_reduce(prim, params, in_specs, in_avals,
                                     out_avals)
        if prim == "transpose":
            return self._rule_transpose(prim, params, in_specs, in_avals,
                                        out_avals)
        if prim in ("slice", "dynamic_slice"):
            return self._rule_slice(prim, params, in_specs, in_avals,
                                    out_avals)
        if prim == "dynamic_update_slice":
            return self._rule_dus(prim, params, in_specs, in_avals,
                                  out_avals)
        if prim == "pad":
            return self._rule_pad(prim, params, in_specs, in_avals,
                                  out_avals)
        if prim == "concatenate":
            return self._rule_concatenate(prim, params, in_specs,
                                          in_avals, out_avals)
        if prim == "gather":
            return self._rule_gather(prim, params, in_specs, in_avals,
                                     out_avals)
        if prim == "reshape":
            return self._rule_reshape(prim, params, in_specs, in_avals,
                                      out_avals)
        if prim == "broadcast_in_dim" and in_specs:
            # map the input spec through broadcast_dimensions; dims the
            # broadcast expands (in size 1 -> out size n) are replicated
            bd = params.get("broadcast_dimensions", ())
            a, o = in_avals[0], out_avals[0]
            out_spec: List[Optional[str]] = [None] * len(o.shape)
            for i, d in enumerate(bd):
                if i < len(a.shape) and a.shape[i] == o.shape[d]:
                    out_spec[d] = in_specs[0][i]
            return [tuple(out_spec)]
        if prim in ("cumsum", "cumprod", "cummax", "cummin",
                    "cumlogsumexp", "sort", "rev"):
            # same OUTPUT shape but data mixes ALONG a dim: elementwise
            # treatment would silently predict zero collectives for a
            # scan/sort over a sharded dim. Conservative: gather the
            # operated dims' axes, keep the rest.
            dims = params.get("dimensions")  # rev
            if dims is None:
                d1 = params.get("dimension", params.get("axis"))
                dims = () if d1 is None else (d1,)  # sort / cum*
            dims = tuple(d for d in dims if d is not None)
            # variadic sort carries (keys, values, ...): EVERY operand's
            # sharding matters and each output mirrors its own operand
            outs = []
            for i, o in enumerate(out_avals):
                spec = in_specs[i] if i < len(in_specs) else ()
                a = in_avals[i] if i < len(in_avals) else out_avals[i]
                out_spec = list(_norm_spec(spec, len(o.shape)))
                lost = [out_spec[d] for d in dims
                        if d < len(out_spec) and out_spec[d] is not None]
                self._record_gathers(prim, a,
                                     tuple(_norm_spec(spec, np.ndim(a))),
                                     lost)
                for d in dims:
                    if d < len(out_spec):
                        out_spec[d] = None
                outs.append(tuple(out_spec))
            return outs
        if prim in ("convert_element_type", "copy",
                    "stop_gradient", "integer_pow", "squeeze"):
            spec = in_specs[0] if in_specs else ()
            out = []
            for o in out_avals:
                out.append(_norm_spec(
                    spec if len(o.shape) == len(in_avals[0].shape)
                    else None, len(o.shape)))
            return out
        # same-shape, scalar, or size-1-broadcast operands ->
        # elementwise merge
        def _bcast_ok(a):
            sh = tuple(getattr(a, "shape", ()))
            osh = tuple(out_avals[0].shape)
            if sh in (osh, ()):
                return True
            return len(sh) == len(osh) and all(
                x == y or x == 1 for x, y in zip(sh, osh))
        if out_avals and all(_bcast_ok(a) for a in in_avals):
            out_ndim = len(out_avals[0].shape)
            full = [_norm_spec(s if np.ndim(a) == out_ndim else None,
                               out_ndim)
                    for s, a in zip(in_specs, in_avals)]
            return self._rule_elementwise(prim, full, in_avals, out_avals)
        # unknown shape-changing primitive: conservative replicate
        out = []
        for s, a in zip(in_specs, in_avals):
            if any(x is not None for x in s):
                self._gather_to_replicated(prim, s, a)
        return [(None,) * len(o.shape) for o in out_avals]


def propagate_sharding(fn, example_args, in_specs,
                       mesh_dims: Dict[str, int],
                       ctx: Optional[CommContext] = None
                       ) -> PropagationReport:
    """Trace ``fn`` and propagate input PartitionSpecs through it.

    in_specs: pytree matching example_args with PartitionSpec / None
    leaves. Returns a PropagationReport: inferred output specs, the
    predicted reshard set (collective, axis, bytes, time) and model
    FLOPs — the Completer+Resharder reasoning XLA performs implicitly,
    surfaced for the planner.
    """
    import jax

    closed = jax.make_jaxpr(fn)(*example_args)
    flat_specs, _ = jax.tree_util.tree_flatten(
        in_specs, is_leaf=lambda x: x is None or not isinstance(
            x, (list, dict)))
    flat_args = jax.tree_util.tree_leaves(example_args)
    if len(flat_specs) != len(flat_args):
        raise ValueError(
            f"in_specs tree ({len(flat_specs)} leaves) does not match "
            f"example_args ({len(flat_args)} leaves)")
    prop = _Propagator(mesh_dims, ctx or CommContext())
    norm = [_norm_spec(s, np.ndim(a))
            for s, a in zip(flat_specs, flat_args)]
    out = prop.run(closed.jaxpr, norm)
    prop.report.out_specs = list(out)
    return prop.report
