"""Semi-automatic parallel — the auto_parallel namespace.

Reference analog: python/paddle/distributed/auto_parallel/ — ProcessMesh
(process_mesh.py:45), shard_tensor/shard_op markers (interface.py:28/:108),
Strategy (strategy.py), Engine (engine.py:57, fit:812) whose pipeline is
build → plan (Completer propagates dist attrs, completion.py:928) →
parallel (Partitioner splits the program per rank, Resharder inserts comm)
→ init (create comm groups).

TPU-native design: the plan/partition/reshard stages ARE XLA's GSPMD
partitioner (SURVEY.md §3.6 — the reference hand-implements exactly this
shape on ProgramDesc). So the Engine here only has to (1) place parameters
on the mesh per their annotations, (2) shard the data batch over the "dp"
axis, (3) jit one training step — everything the reference's Completer/
Partitioner/Resharder do is done by the compiler from those annotations.
"""
from __future__ import annotations

from .placements import Shard, Replicate, Partial, to_partition_spec
from .strategy import Strategy
from .engine import Engine
from .planner import ShardingPlanner
from . import cost_model
from ..mesh import ProcessMesh, get_mesh
from ..shard import (shard_tensor, shard_op, shard_layer,
                     with_sharding_constraint, shard_params,
                     replicate_params)
from ..recompute import recompute

__all__ = [
    "ProcessMesh", "Engine", "Strategy",
    "Shard", "Replicate", "Partial", "to_partition_spec",
    "shard_tensor", "shard_op", "shard_layer", "with_sharding_constraint",
    "shard_params", "replicate_params", "recompute", "get_mesh",
]
