"""auto_parallel.Strategy — parallelization/optimization knobs.

Reference analog: python/paddle/distributed/auto_parallel/strategy.py
(config groups defined by constants.py: amp, recompute, sharding,
gradient_merge, pipeline, qat, tuning). Field names kept identical so user
configs port unchanged; each group notes what it means on TPU.
"""
from __future__ import annotations

__all__ = ["Strategy"]


class _Config:
    _fields = {}

    def __init__(self, **kwargs):
        for k, v in self._fields.items():
            setattr(self, k, v)
        for k, v in kwargs.items():
            setattr(self, k, v)

    def to_dict(self):
        return {k: getattr(self, k) for k in self._fields}

    def __repr__(self):
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._fields)
        return f"{type(self).__name__}({inner})"


class AMPConfig(_Config):
    """On TPU: dtype='bfloat16' needs no loss scaling; fp16 keeps the
    scaler for parity (amp/GradScaler)."""
    _fields = dict(enable=False, dtype="bfloat16", level="o1",
                   init_loss_scaling=32768.0, custom_white_list=[],
                   custom_black_list=[], use_fp16_guard=False,
                   use_bf16_guard=False)


class RecomputeConfig(_Config):
    """Lowered to jax.checkpoint regions (distributed/recompute.py)."""
    _fields = dict(enable=False, checkpoints=None, no_recompute_segments=[],
                   enable_tuning=False)


class ShardingConfig(_Config):
    """ZeRO: stage 1/2 = optimizer-state (+grad) sharding over 'dp' via
    PartitionSpec; stage 3 = param sharding (GSPMD gathers per-use)."""
    _fields = dict(enable=False, stage=1, degree=8,
                   enable_tuning=False, overlap_grad_comm=True)


class GradientMergeConfig(_Config):
    _fields = dict(enable=False, k_steps=1, avg=True)


class PipelineConfig(_Config):
    _fields = dict(enable=False, schedule_mode="1F1B", micro_batch_size=1,
                   accumulate_steps=1)


class QATConfig(_Config):
    _fields = dict(enable=False, channel_wise_abs_max=True, weight_bits=8,
                   activation_bits=8, not_quant_pattern=["skip_quant"])


class TuningConfig(_Config):
    _fields = dict(enable=False, profile_start_step=1, profile_end_step=1,
                   run_after_tuning=True, verbose=True)


class Strategy(_Config):
    """reference: strategy.py Strategy — holds one config object per
    optimization; `auto_mode` "semi" means user annotations + automatic
    propagation (on TPU: annotations + GSPMD)."""

    _fields = dict(auto_mode="semi", seed=None, split_data=True,
                   data_parallel=True)

    def __init__(self, config=None):
        super().__init__(**(config or {}))
        self.amp = AMPConfig()
        self.recompute = RecomputeConfig()
        self.sharding = ShardingConfig()
        self.gradient_merge = GradientMergeConfig()
        self.pipeline = PipelineConfig()
        self.qat = QATConfig()
        self.tuning = TuningConfig()

    def to_dict(self):
        d = super().to_dict()
        for g in ("amp", "recompute", "sharding", "gradient_merge",
                  "pipeline", "qat", "tuning"):
            d[g] = getattr(self, g).to_dict()
        return d
