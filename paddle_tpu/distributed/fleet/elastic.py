"""Failure detection: heartbeats + staleness monitor.

Reference analog: python/paddle/distributed/fleet/elastic/manager.py:126
(ElasticManager — etcd-registered node heartbeats, a watchdog that
declares nodes dead and triggers pod restart). Scale-in/scale-out
membership changes are out of scope for now; what this provides is the
failure-detection half: process EXITS are caught by the launcher's
poll-based watchdog, and in-process HANGS are caught here through
heartbeat staleness.

TPU-native shape: heartbeats ride the same native TCPStore the launcher
already serves for rendezvous (csrc/tcp_store.cc) — no etcd. Each beat
is a counter increment; the monitor compares counter *changes* against
its own clock, so worker/launcher clock skew cannot cause false
positives. Workers opt in by calling ``start_heartbeat()`` (typically
right after init_parallel_env); ranks that never beat are not monitored,
so scripts that don't cooperate simply keep exit-code-only supervision.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["start_heartbeat", "HeartbeatMonitor"]


def _hb_key(job_id: str, restart: str, rank: str) -> str:
    return f"hb/{job_id}/{restart}/{rank}"


def start_heartbeat(interval: float = 2.0, store=None) -> threading.Event:
    """Worker side: beat into the job's TCPStore from a daemon thread.
    Env contract comes from the launcher (PADDLE_MASTER / PADDLE_JOB_ID /
    PADDLE_TRAINER_ID / PADDLE_RESTART_COUNT). Returns a stop Event."""
    if store is None:
        from ..store import TCPStore
        host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=False, timeout=60)
        if not store.is_native:
            import warnings
            warnings.warn(
                "TCPStore fell back to the in-process store: heartbeats "
                "cannot reach the launcher, so --heartbeat_timeout will "
                "not detect hangs on this host")
    key = _hb_key(os.environ.get("PADDLE_JOB_ID", "default"),
                  os.environ.get("PADDLE_RESTART_COUNT", "0"),
                  os.environ.get("PADDLE_TRAINER_ID", "0"))
    stop = threading.Event()

    # one synchronous beat before the thread starts: the rank is
    # monitored from the moment start_heartbeat returns, even if it
    # hangs (or the scheduler starves the thread) immediately after
    store.add(key, 1)

    def beat():
        while not stop.is_set():
            stop.wait(interval)
            try:
                store.add(key, 1)
            except Exception:
                return  # store gone: the pod is coming down anyway

    threading.Thread(target=beat, daemon=True,
                     name="paddle-tpu-heartbeat").start()
    return stop


class HeartbeatMonitor:
    """Launcher side: declare a rank hung when its counter stops moving
    for longer than ``timeout`` (measured on the monitor's clock)."""

    def __init__(self, store, job_id: str, nproc: int, timeout: float):
        self._store = store
        self._job_id = job_id
        self._nproc = nproc
        self._timeout = timeout
        # rank -> (last counter value, monitor time it last changed)
        self._seen: Dict[int, tuple] = {}

    def reset(self):
        self._seen.clear()

    def stale_ranks(self, restart_count: int,
                    now: Optional[float] = None) -> List[int]:
        # monotonic: an NTP step on the launcher must not declare every
        # healthy rank hung
        now = time.monotonic() if now is None else now
        stale = []
        for rank in range(self._nproc):
            key = _hb_key(self._job_id, str(restart_count), str(rank))
            raw = self._store.get(key)
            if raw is None:
                continue  # never beat: not monitored (opt-in contract)
            try:
                val = int(raw)
            except ValueError:
                continue
            prev = self._seen.get(rank)
            if prev is None or prev[0] != val:
                self._seen[rank] = (val, now)
            elif now - prev[1] > self._timeout:
                stale.append(rank)
        return stale
