"""Elastic training: heartbeats, staleness monitor, relaunch protocol,
scale up/down.

Reference analog: python/paddle/distributed/fleet/elastic/manager.py:126
(ElasticManager — etcd-registered node heartbeats, a watchdog that
declares nodes dead and triggers pod restart, scale up/down by watching
membership, and the exit-code relaunch protocol: a worker exiting with
code 101 asks to be relaunched rather than counted as failed). Three
halves here:

- failure detection: process EXITS are caught by the launcher's
  poll-based watchdog, in-process HANGS by heartbeat staleness
  (``start_heartbeat`` / ``HeartbeatMonitor``);
- cooperative relaunch: ``ElasticJob`` honors RELAUNCH_EXIT_CODE without
  consuming the restart budget (manager.py's exit-code-101 contract);
- scale events: the world size is a watched key in the job's TCPStore
  (``request_scale`` writes it — the etcd-watch analog); on change the
  gang is torn down and respawned at the new size, clamped to
  [min_nproc, max_nproc], with PADDLE_TRAINERS_NUM re-rendered. Workers
  resume from their latest checkpoint (distributed.checkpoint restores
  across mesh shapes, so a different world size is a supported resume).

TPU-native shape: heartbeats ride the same native TCPStore the launcher
already serves for rendezvous (csrc/tcp_store.cc) — no etcd. Each beat
is a counter increment; the monitor compares counter *changes* against
its own clock, so worker/launcher clock skew cannot cause false
positives. Workers opt in by calling ``start_heartbeat()`` (typically
right after init_parallel_env); ranks that never beat are not monitored,
so scripts that don't cooperate simply keep exit-code-only supervision.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["start_heartbeat", "HeartbeatMonitor", "ElasticJob",
           "request_scale", "RELAUNCH_EXIT_CODE"]

# Worker exit code meaning "relaunch me" (checkpoint saved, membership
# changed, re-plan wanted...). Reference: manager.py's exit-code-101
# protocol (ELASTIC_AUTO_PARALLEL_EXIT_CODE plays the same role for
# re-planning). Does not consume the restart budget.
RELAUNCH_EXIT_CODE = 101


def _hb_key(job_id: str, restart: str, rank: str) -> str:
    return f"hb/{job_id}/{restart}/{rank}"


def start_heartbeat(interval: float = 2.0, store=None) -> threading.Event:
    """Worker side: beat into the job's TCPStore from a daemon thread.
    Env contract comes from the launcher (PADDLE_MASTER / PADDLE_JOB_ID /
    PADDLE_TRAINER_ID / PADDLE_RESTART_COUNT). Returns a stop Event."""
    if store is None:
        from ..store import TCPStore
        host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=False, timeout=60)
        if not store.is_native:
            import warnings
            warnings.warn(
                "TCPStore fell back to the in-process store: heartbeats "
                "cannot reach the launcher, so --heartbeat_timeout will "
                "not detect hangs on this host")
    key = _hb_key(os.environ.get("PADDLE_JOB_ID", "default"),
                  os.environ.get("PADDLE_RESTART_COUNT", "0"),
                  os.environ.get("PADDLE_TRAINER_ID", "0"))
    stop = threading.Event()

    # one synchronous beat before the thread starts: the rank is
    # monitored from the moment start_heartbeat returns, even if it
    # hangs (or the scheduler starves the thread) immediately after
    store.add(key, 1)

    def beat():
        from ...testing.chaos import chaos_point
        while not stop.is_set():
            stop.wait(interval)
            try:
                # chaos "hang@elastic.heartbeat" stalls the beat so tests
                # can prove the monitor declares this rank hung
                chaos_point("elastic.heartbeat", path=None, key=key)
                store.add(key, 1)
            except Exception:
                return  # store gone: the pod is coming down anyway

    threading.Thread(target=beat, daemon=True,
                     name="paddle-tpu-heartbeat").start()
    return stop


class HeartbeatMonitor:
    """Launcher side: declare a rank hung when its counter stops moving
    for longer than ``timeout`` (measured on the monitor's clock)."""

    def __init__(self, store, job_id: str, nproc: int, timeout: float):
        self._store = store
        self._job_id = job_id
        self.nproc = nproc  # public: elastic rescales adjust it
        self._timeout = timeout
        # rank -> (last counter value, monitor time it last changed)
        self._seen: Dict[int, tuple] = {}

    def reset(self):
        self._seen.clear()

    def stale_ranks(self, restart_count: int,
                    now: Optional[float] = None) -> List[int]:
        # monotonic: an NTP step on the launcher must not declare every
        # healthy rank hung
        now = time.monotonic() if now is None else now
        stale = []
        for rank in range(self.nproc):
            key = _hb_key(self._job_id, str(restart_count), str(rank))
            raw = self._store.get(key)
            if raw is None:
                continue  # never beat: not monitored (opt-in contract)
            try:
                val = int(raw)
            except ValueError:
                continue
            prev = self._seen.get(rank)
            if prev is None or prev[0] != val:
                self._seen[rank] = (val, now)
            elif now - prev[1] > self._timeout:
                stale.append(rank)
        return stale


def _scale_key(job_id: str) -> str:
    return f"elastic/{job_id}/world_size"


def request_scale(master: str, job_id: str, nproc: int, store=None):
    """Operator side: ask a running ElasticJob to change its world size
    (the etcd-watch analog — any party with store access can scale the
    job). ``master`` is the job's ``host:port`` rendezvous address."""
    if store is None:
        from ..store import TCPStore
        host, port = master.rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=False, timeout=60)
        if not store.is_native:
            # the fallback store is process-local: set() would write into
            # THIS process's dict and the job would never see the key
            raise RuntimeError(
                "request_scale needs the native TCPStore client to reach "
                f"the job at {master} (build csrc/: make -C csrc); the "
                "in-process fallback cannot deliver scale requests")
    store.set(_scale_key(job_id), str(int(nproc)).encode())


from ..launch import LocalJob  # noqa: E402  (no import cycle: launch only
# imports fleet.elastic lazily inside functions)


class ElasticJob(LocalJob):
    """Elastic pod supervisor (ElasticManager analog, a LocalJob
    subclass overriding the _check_rescale extension point + run loop).

    Differences from a fixed LocalJob pod:
    - world size follows the store's scale key, clamped to
      [min_nproc, max_nproc]; a change tears the gang down and respawns
      at the new size without consuming the restart budget;
    - a worker exiting RELAUNCH_EXIT_CODE triggers a free gang relaunch;
    - every (re)launch increments PADDLE_RESTART_COUNT so heartbeat keys
      and rendezvous epochs never collide across generations.
    """

    def __init__(self, script, script_args, nproc, min_nproc=1,
                 max_nproc=None, **job_kwargs):
        super().__init__(script, script_args, int(nproc), **job_kwargs)
        self.min_nproc = max(1, int(min_nproc))
        self.max_nproc = int(max_nproc) if max_nproc else int(nproc)
        self._last_scale_raw = None
        self._failures = 0  # real failures only; free relaunches excluded

    # -- scale watching -----------------------------------------------------
    def _read_scale(self):
        """ONE store read -> (raw, want). All scale decisions in a cycle
        derive from the same raw value, so a request landing between two
        reads can never be half-seen and dropped."""
        raw = self._store.get(_scale_key(self.job_id))
        if raw is None:
            return None, None
        try:
            want = max(self.min_nproc, min(self.max_nproc, int(raw)))
        except ValueError:
            return raw, None
        return raw, want

    def _check_rescale(self) -> bool:
        raw, want = self._read_scale()
        if raw is None or raw == self._last_scale_raw:
            return False
        if want is None:
            sys.stderr.write(
                f"elastic: scale request {raw!r} is not an integer; "
                "ignoring\n")
            self._last_scale_raw = raw
            return False
        if want == self.nproc:
            # clamped/identical: tell the operator once rather than
            # silently swallowing the request
            sys.stderr.write(
                f"elastic: scale request {raw!r} resolves to the current "
                f"world size {self.nproc} (bounds [{self.min_nproc}, "
                f"{self.max_nproc}]); ignoring\n")
            self._last_scale_raw = raw
            return False
        return True

    # -- supervision --------------------------------------------------------
    def run(self, poll_interval: float = 0.2) -> int:
        if self._store is None:
            self._start_store()
        while True:
            raw, want = self._read_scale()
            self._last_scale_raw = raw
            if want is not None and want != self.nproc:
                self.nproc = want
                if self._monitor is not None:
                    self._monitor.nproc = want
            workers = [self._spawn_one(r) for r in range(self.nproc)]
            rc = self._watch(workers, poll_interval)
            if rc == 0:
                return 0
            # every respawn is a new generation: PADDLE_RESTART_COUNT (and
            # with it the heartbeat/rendezvous epoch) must never repeat
            self.restart_count += 1
            if rc == self.RESCALE_RC:
                sys.stderr.write(
                    "elastic: scale event; respawning gang at the new "
                    "world size\n")
                continue
            if rc == RELAUNCH_EXIT_CODE:
                sys.stderr.write(
                    "elastic: worker requested relaunch (exit 101); "
                    "respawning gang\n")
                continue
            self._failures += 1
            if self._failures > self.max_restarts:
                sys.stderr.write(
                    f"elastic: pod failed rc={rc} after exhausting "
                    f"{self.max_restarts} restarts; giving up\n")
                return rc
            sys.stderr.write(
                f"elastic: worker failure rc={rc}; gang restart "
                f"{self._failures}/{self.max_restarts}\n")

    @property
    def master(self) -> str:
        return f"{self.master_host}:{self.master_port}"
