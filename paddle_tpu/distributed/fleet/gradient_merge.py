"""Gradient merge (k-step gradient accumulation) meta-optimizer.

Reference analog: fleet/meta_optimizers/gradient_merge_optimizer.py and
the dygraph accumulate_steps contract of pipeline_parallel — gradients
from k micro-steps merge into one optimizer application, simulating a
k-times-larger global batch without the memory.

TPU-native: the eager tape already accumulates into p.grad across
backward() calls, so the wrapper's job is the CADENCE — count steps,
only let the inner optimizer (and LR schedule) advance every k-th call,
and average the merged gradient when `avg` (the reference default).
Works in eager loops and inside compiled steps (the counter is python
state at trace time for the former, and hapi/engine drive it per real
step).
"""
from __future__ import annotations

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._step_i = 0

    # passthrough surface
    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def inner_opt(self):
        return self._inner

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Eager: backward + merged step (the reference meta-optimizer's
        apply cadence). Static programs apply the optimizer once per
        Executor.run inside the compiled step, where k-step accumulation
        must be expressed in the program itself — refuse loudly rather
        than silently running unmerged."""
        from ...static.program import recording_program
        if recording_program() is not None:
            raise NotImplementedError(
                "gradient_merge with static-mode minimize(): drive the "
                "merge cadence from the training loop instead (eager "
                "backward()+step(), or scale accumulate_steps in the "
                "pipeline/hapi config)")
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._inner._parameter_list]

    def step(self):
        self._step_i += 1
        if self._step_i % self.k_steps:
            return  # keep accumulating; do NOT clear grads between
        if self.avg and self.k_steps > 1:
            for p in self._inner._parameter_list:
                if p.grad is not None:
                    p.grad = p.grad * (1.0 / self.k_steps)
        self._inner.step()
        self._inner.clear_grad()

    def clear_grad(self, set_to_zero=True):
        # between merged applications the accumulated grads must
        # survive the user's step()/clear_grad() loop idiom; only a
        # boundary (just-applied) clear is real
        if self._step_i % self.k_steps == 0:
            self._inner.clear_grad(set_to_zero)

    def state_dict(self):
        # the accumulated grads (p.grad) are NOT part of optimizer
        # state: a checkpoint taken mid-accumulation resumes at the last
        # BOUNDARY — persisting the raw counter would make the first
        # post-restore boundary average k grads while only having
        # accumulated the post-restore ones
        sd = self._inner.state_dict()
        sd["__gm_step__"] = self._step_i - (self._step_i % self.k_steps)
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        self._step_i = int(sd.pop("__gm_step__", 0))
        self._inner.set_state_dict(sd)
