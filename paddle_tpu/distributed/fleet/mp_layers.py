"""Tensor-parallel (megatron-style) layers.

Reference analog: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding(:35), ColumnParallelLinear(:173),
RowParallelLinear(:332), ParallelCrossEntropy(:498), with comm primitives
from mp_ops.py (_c_identity/_c_concat/_c_split/_mp_allreduce).

TPU-native: the layers hold FULL logical weights annotated with
PartitionSpecs over the 'mp' mesh axis; under jit with the global mesh,
GSPMD partitions them and inserts the identity/allreduce collectives that
mp_ops.py implements manually (SURVEY.md §7 capability map). The
`sharding_spec()` of each parameter is the contract the trainer's pjit
in/out shardings consume. gather_output/input_is_parallel semantics are
expressed as output sharding constraints.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ...core.tensor import Tensor, apply_op
from ...nn.layer.layers import Layer
from ...nn import functional as F
from ...nn import initializer as I
from ..mesh import get_topology, get_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "mark_sharding"]


def mark_sharding(param: Tensor, spec: PartitionSpec):
    """Attach the GSPMD annotation; consumed by parallelize_module /
    shard_params when materializing onto the mesh."""
    param.sharding_spec = spec
    return param


def _constraint(x: Tensor, spec: PartitionSpec) -> Tensor:
    """with_sharding_constraint at the Tensor level (traced only)."""
    def _f(a):
        if isinstance(a, jax.core.Tracer) and get_mesh() is not None:
            return jax.lax.with_sharding_constraint(
                a, jax.sharding.NamedSharding(get_mesh(), spec))
        return a
    return apply_op(_f, x, op_name="sharding_constraint")


class ColumnParallelLinear(Layer):
    """Weight [in, out] sharded on out ('mp'); forward keeps the output
    sharded (gather_output=False) or constrains it replicated."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in = in_features
        self._out = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, PartitionSpec(None, "mp"))
        self.bias = self.create_parameter(
            [out_features], attr=None if has_bias else False, is_bias=True,
            default_initializer=I.Constant(0.0)) if has_bias else None
        if self.bias is not None:
            mark_sharding(self.bias, PartitionSpec("mp"))

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constraint(out, PartitionSpec())
        return _constraint(out, PartitionSpec(None, None, "mp")
                           if out.ndim == 3 else PartitionSpec(None, "mp"))


class RowParallelLinear(Layer):
    """Weight [in, out] sharded on in ('mp'); partial results are psum'd by
    GSPMD (the _mp_allreduce analog)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        mark_sharding(self.weight, PartitionSpec("mp", None))
        self.bias = self.create_parameter(
            [out_features], attr=None if has_bias else False, is_bias=True,
            default_initializer=I.Constant(0.0)) if has_bias else None
        if self.bias is not None:
            mark_sharding(self.bias, PartitionSpec())

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        return _constraint(out, PartitionSpec())


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over vocab ('mp'); GSPMD turns the gather
    into a sharded lookup + psum of masked partials (the reference's
    c_embedding + allreduce, mp_layers.py:35)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        mark_sharding(self.weight, PartitionSpec("mp", None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE (reference mp_layers.py:498 over
    c_softmax_with_cross_entropy_op). With logits sharded on the class
    axis, the log-softmax reductions auto-psum over 'mp' under GSPMD; the
    explicit-collective shard_map variant lives in
    distributed.parallel_ce for pedagogy/tests."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        logits = _constraint(input, PartitionSpec(None, None, "mp")
                             if input.ndim == 3
                             else PartitionSpec(None, "mp"))
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)
