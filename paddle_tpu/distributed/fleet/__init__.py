"""Fleet — hybrid-parallel training facade.

Reference analog: python/paddle/distributed/fleet/ (Fleet.init at
fleet.py:169, _init_hybrid_parallel_env:385 building the 4-D topology,
distributed_model wrapping in Pipeline/Tensor/Sharding/DataParallel,
HybridParallelOptimizer).

TPU-native: fleet.init builds the ONE global Mesh from
DistributedStrategy.hybrid_configs; distributed_model returns the model
(sharding comes from parameter PartitionSpec annotations + the jit step);
distributed_optimizer wraps grad-clip with the mesh-aware global-norm.
"""
from __future__ import annotations

from typing import Optional

from ..mesh import init_mesh, get_topology, HybridTopology
from ..parallel import init_parallel_env, DataParallel
from ..collective import get_rank, get_world_size
from . import mp_layers
from . import utils
from . import elastic
from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,
                        RowParallelLinear, ParallelCrossEntropy)
from .. import auto_parallel as auto  # `from fleet import auto` parity

__all__ = ["init", "Fleet", "DistributedStrategy", "distributed_model",
            "distributed_optimizer", "get_hybrid_communicate_group",
            "worker_index", "worker_num", "is_first_worker",
            "VocabParallelEmbedding", "ColumnParallelLinear",
            "RowParallelLinear", "ParallelCrossEntropy", "mp_layers",
            "utils", "auto"]


class DistributedStrategy:
    """reference: fleet/base/distributed_strategy.py (protobuf-backed).
    Keeps the same field names for the knobs that matter on TPU."""

    def __init__(self):
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.sharding = False
        self.sharding_configs = {}
        self.lamb = False
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001,
                             "lars_weight_decay": 0.0005,
                             "epsilon": 0.0,
                             "exclude_from_weight_decay": []}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        # DGC (deep gradient compression) is a reasoned non-goal on TPU:
        # it trades compute for bandwidth on commodity interconnects,
        # while ICI all-reduces are compiler-scheduled, overlapped with
        # backward compute, and not the bottleneck the strategy exists
        # for. distributed_optimizer raises if enabled.
        self.dgc = False
        self.dgc_configs = {}
        self.find_unused_parameters = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}


_FLEET_STATE = {"strategy": None, "topology": None}


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    strategy = strategy or DistributedStrategy()
    init_parallel_env()
    hc = strategy.hybrid_configs
    topo = init_mesh(dp=hc.get("dp_degree", 1), pp=hc.get("pp_degree", 1),
                     sharding=hc.get("sharding_degree", 1),
                     mp=hc.get("mp_degree", 1))
    _FLEET_STATE["strategy"] = strategy
    _FLEET_STATE["topology"] = topo
    return topo


def get_hybrid_communicate_group() -> Optional[HybridTopology]:
    return _FLEET_STATE["topology"] or get_topology()


def distributed_model(model):
    """reference: fleet/model.py:30. On TPU the model is already
    mesh-ready (parameters carry PartitionSpecs); DP-only models get the
    DataParallel wrapper for API parity."""
    topo = get_hybrid_communicate_group()
    if topo is not None and (topo.mp_degree > 1 or topo.pp_degree > 1):
        return model
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """reference: HybridParallelOptimizer
    (dygraph_optimizer/hybrid_parallel_optimizer.py:186). Grad clip is
    already global under GSPMD (grads are full logical tensors in
    trace), so the base wrapper is the optimizer itself; the
    gradient_merge strategy (meta_optimizers/gradient_merge_optimizer)
    wraps it in k-step accumulation."""
    strategy = strategy or _FLEET_STATE.get("strategy")
    if strategy is None:
        return optimizer
    if getattr(strategy, "dgc", False):
        raise NotImplementedError(
            "DGC is a reasoned non-goal on TPU: gradient compression "
            "trades compute for bandwidth on commodity interconnects; "
            "ICI all-reduces are compiler-scheduled and overlapped with "
            "backward compute. Use gradient_merge or localsgd to cut "
            "synchronization frequency instead.")
    if getattr(strategy, "lars", False):
        # reference lars meta-optimizer: swap a Momentum inner optimizer
        # for LarsMomentum with the strategy's coefficients, forwarding
        # the inner optimizer's own regularization (the reference passes
        # regularization=opt.regularization through)
        from ...optimizer import LarsMomentum, Momentum
        if isinstance(optimizer, Momentum):
            cfg = getattr(strategy, "lars_configs", {}) or {}
            if getattr(optimizer, "_nesterov", False):
                import warnings
                warnings.warn(
                    "strategy.lars replaces Momentum with LarsMomentum, "
                    "which (like the reference lars_momentum kernel) has "
                    "no nesterov variant; use_nesterov is dropped")
            lars = LarsMomentum(
                learning_rate=optimizer._lr,
                momentum=optimizer._momentum,
                parameters=optimizer._parameter_list,
                grad_clip=optimizer._grad_clip,
                lars_coeff=float(cfg.get("lars_coeff", 0.001)),
                lars_weight_decay=float(
                    cfg.get("lars_weight_decay", 0.0005)),
                epsilon=float(cfg.get("epsilon", 0.0)),
                exclude_from_weight_decay=cfg.get(
                    "exclude_from_weight_decay", []))
            # the Momentum's additive L2 survives alongside the
            # in-ratio lars decay (base-class decay path)
            lars._weight_decay = optimizer._weight_decay
            optimizer = lars
    if getattr(strategy, "localsgd", False):
        from .localsgd import LocalSGDOptimizer
        cfg = getattr(strategy, "localsgd_configs", {}) or {}
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=int(cfg.get("k_steps", 1)))
    if getattr(strategy, "gradient_merge", False):
        from .gradient_merge import GradientMergeOptimizer
        cfg = getattr(strategy, "gradient_merge_configs", {}) or {}
        return GradientMergeOptimizer(
            optimizer, k_steps=int(cfg.get("k_steps", 1)),
            avg=bool(cfg.get("avg", True)))
    return optimizer


class Fleet:
    """reference: fleet/fleet.py:101 — the stateful facade object. The
    module-level `fleet.init` etc. mirror paddle, where a singleton Fleet
    instance backs the module functions."""

    def __init__(self):
        self._strategy = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        init(role_maker, is_collective, strategy, log_level)
        self._strategy = _FLEET_STATE["strategy"]
        return self

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    @property
    def worker_index(self):
        return get_rank()

    @property
    def worker_num(self):
        return get_world_size()

    def is_first_worker(self):
        return get_rank() == 0

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def get_hybrid_communicate_group(self):
        return get_hybrid_communicate_group()

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        raise NotImplementedError(
            "PS-mode persistables are out of scope on TPU; use "
            "paddle_tpu.save(model.state_dict(), path)")


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()
