"""LocalSGD meta-optimizer: k local steps, then parameter averaging.

Reference analog: fleet/meta_optimizers/localsgd_optimizer.py
(LocalSGDOptimizer — trainers run k_steps of UN-synchronized SGD on
their local batch shard, then all-reduce-average the PARAMETERS; the
per-step gradient all-reduce of plain DP disappears, trading a little
convergence noise for k-fold less communication).

TPU-native: two surfaces.

1. ``localsgd_round(train_step, k_steps, axis)`` — the compiled form:
   wraps a per-replica functional train step into one round = a
   ``lax.scan`` of k local steps (no collectives inside) followed by a
   single ``pmean`` of the params over the dp axis. Run it under
   ``shard_map`` with the params given a leading per-replica dimension;
   XLA compiles the whole round onto ICI with exactly one all-reduce
   per k steps.

2. ``LocalSGDOptimizer(inner, k_steps)`` — the eager facade with the
   reference's class shape: every step applies the inner optimizer
   locally; each k-th step averages the parameters over the dp group
   (identity on one process, ``lax.pmean`` inside a trace — same
   contract as the rest of distributed.collective's eager facade).

The adaptive variant (AdaptiveLocalSGDOptimizer, which retunes k from
loss variance) is intentionally out of scope: its schedule is python-
side control flow retuning a compile-time constant; retrace cost on TPU
would eat the communication win. DGC (deep gradient compression) is
likewise out of scope as a strategy: it targets bandwidth-starved
commodity clusters, while ICI all-reduce is compiler-scheduled and
overlapped — documented in DistributedStrategy.
"""
from __future__ import annotations

__all__ = ["localsgd_round", "LocalSGDOptimizer"]


def localsgd_round(train_step, k_steps: int, axis: str = "dp"):
    """Build the compiled one-round function.

    ``train_step(params, batch) -> (params, aux)`` must be collective-
    free (pure local SGD). Returns ``round_fn(params, batches)`` where
    ``batches`` stacks k local microbatches on a leading axis; the
    result's params are pmean'd over ``axis``.
    """
    import jax
    from jax import lax

    if k_steps < 1:
        raise ValueError(f"k_steps must be >= 1, got {k_steps}")

    def round_fn(params, batches):
        def body(p, b):
            return train_step(p, b)
        params, auxs = lax.scan(body, params, batches, length=k_steps)
        params = jax.tree_util.tree_map(
            lambda a: lax.pmean(a, axis), params)
        return params, auxs

    return round_fn


class LocalSGDOptimizer:
    """Eager wrapper: local inner steps + k-cadence parameter average
    over the dp group (reference LocalSGDOptimizer's begin_step/
    communicate cadence)."""

    def __init__(self, inner_optimizer, k_steps: int = 1, group=None):
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = inner_optimizer
        self.k_steps = int(k_steps)
        self._group = group
        self._step_i = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def inner_opt(self):
        return self._inner

    def step(self):
        self._inner.step()
        self._step_i += 1
        if self._step_i % self.k_steps == 0:
            self._sync_params()

    def _sync_params(self):
        from ..collective import ReduceOp, all_reduce
        for p in self._inner._parameter_list:
            all_reduce(p, op=ReduceOp.AVG, group=self._group)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._inner._parameter_list]
