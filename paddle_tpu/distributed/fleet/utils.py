"""fleet.utils — recompute + hybrid-parallel helpers.

Reference analog: python/paddle/distributed/fleet/utils/__init__.py
(exports `recompute`) and fleet/utils/hybrid_parallel_util.py
(fused_allreduce_gradients:206).
"""
from __future__ import annotations

from ..recompute import recompute, recompute_sequential  # noqa: F401

__all__ = ["recompute", "recompute_sequential",
           "fused_allreduce_gradients", "LocalFS"]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """reference: hybrid_parallel_util.py:206 — DP bucketed grad allreduce.
    Under GSPMD the gradients computed inside the jit'ed step are already
    globally reduced over the 'dp' axis (psum inserted by the partitioner),
    so this is an intentional no-op kept for call-site parity."""
    return None


class LocalFS:
    """reference: fleet/utils/fs.py LocalFS (HDFS client is out of scope
    on TPU pods; GCS/local posix is the native storage)."""

    def ls_dir(self, path):
        import os
        entries = os.listdir(path)
        dirs = [e for e in entries
                if os.path.isdir(os.path.join(path, e))]
        files = [e for e in entries
                 if os.path.isfile(os.path.join(path, e))]
        return dirs, files

    def is_exist(self, path):
        import os
        return os.path.exists(path)

    def mkdirs(self, path):
        import os
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        import os
        import shutil
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)
