"""Collective communication API.

Reference analog: python/paddle/distributed/collective.py +
communication/ (all_reduce/all_gather/... over ProcessGroupNCCL,
paddle/fluid/distributed/collective/process_group.h:53).

TPU-native: collectives are XLA ops (lax.psum / all_gather / ppermute /
all_to_all) over named mesh axes. Two modes:

1. **Traced** (inside shard_map/pjit): the functions below call the lax
   collective directly — this is the hot path, compiled onto ICI.
2. **Eager facade**: outside a trace there is nothing to communicate with
   on a single process; the ops are the mathematical identity for
   world_size==1 (matching the reference's behavior for a 1-rank group)
   and raise for multi-host eager use, which the reference also routes
   through compiled programs in practice.

Groups: a `Group` names a mesh axis (or tuple of axes) — the ring-id
analog.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..testing.chaos import chaos_point
from .mesh import get_mesh

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "all_reduce",
           "all_gather", "all_gather_object", "broadcast", "reduce",
           "scatter", "alltoall", "all_to_all", "send", "recv", "reduce_scatter",
           "barrier", "get_rank", "get_world_size", "is_initialized",
           "destroy_process_group", "wait", "stream"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Names one or more mesh axes (the process-group analog)."""

    def __init__(self, axis="dp", ranks=None, gid=0):
        self.axis = axis
        self.ranks = ranks
        self.id = gid

    @property
    def nranks(self):
        mesh = get_mesh()
        if mesh is None:
            return 1
        ax = self.axis
        if isinstance(ax, (tuple, list)):
            return int(np.prod([mesh.shape[a] for a in ax]))
        return mesh.shape.get(ax, 1)

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(axis={self.axis})"


_GROUPS = {0: Group("dp", gid=0)}
_NEXT_GID = [1]


def new_group(ranks=None, backend=None, timeout=None, axis=None):
    gid = _NEXT_GID[0]
    _NEXT_GID[0] += 1
    g = Group(axis or "dp", ranks, gid)
    _GROUPS[gid] = g
    return g


def get_group(gid=0):
    return _GROUPS.get(gid)


def get_rank(group=None):
    import os
    return int(os.environ.get("PADDLE_TRAINER_ID",
                              jax.process_index()))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    import os
    return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))


def is_initialized():
    return True


def destroy_process_group(group=None):
    """Destroying the global group tears down the gang (reference:
    collective.destroy_process_group); named sub-groups are views over
    the mesh with nothing to free."""
    if group is None:
        from .parallel import shutdown
        shutdown()


def barrier(group=None):
    jnp.zeros(()).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not isinstance(
            tensor._array, jax.core.Tracer):
        tensor._array.block_until_ready()


def _axis_of(group):
    if group is None:
        return "dp"
    if isinstance(group, Group):
        return group.axis
    if isinstance(group, str):
        return group
    return "dp"


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# collectives — lax under trace, identity on 1-rank eager
# ---------------------------------------------------------------------------

def _apply_collective(f, tensor, op_name):
    """apply_op with telemetry and health instrumentation: a host span
    when a profiler is live; when FLAGS_tpu_metrics is on, bytes-moved
    counters + a latency histogram per collective op; when a runtime
    HealthMonitor is installed, an entry/exit beacon (so a rank that
    enters and never exits is detected within the collective deadline)
    plus a ``collective.<op>`` chaos point for hang injection. The
    un-instrumented path costs one list truthiness check, one
    dict-lookup+bool (metrics.enabled), and two module-global None
    checks (health hook, chaos hook)."""
    from ..profiler import _record_span, metrics as _metrics, \
        trace as _trace
    from ..runtime import health as _health
    rec = _metrics.enabled()
    t0 = time.perf_counter() if rec else None
    span_name = f"collective/{op_name}"
    # the health beacon promoted to a first-class trace span: when
    # FLAGS_tpu_trace is on, every collective entry/exit lands in the
    # flight recorder with its duration (disabled: one dict lookup)
    with _record_span(span_name), _trace.span(span_name, op=op_name):
        # beacon outermost: the chaos hang below must count as "inside
        # the collective" so self-detection sees the overdue beacon
        with _health.collective_beacon(op_name):
            chaos_point(f"collective.{op_name}",
                        step=_health.current_step())
            out = apply_op(f, tensor, op_name=op_name)
    if rec:
        a = getattr(tensor, "_array", tensor)
        try:
            nbytes = int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
        except Exception:
            nbytes = 0
        _metrics.counter("collective_calls_total",
                         "Collective invocations", op=op_name).inc()
        _metrics.counter("collective_bytes_total",
                         "Input bytes handed to collectives",
                         op=op_name).inc(nbytes)
        _metrics.histogram("collective_latency_seconds",
                           "Host wall time per collective call (trace "
                           "time under jit/shard_map)",
                           op=op_name).observe(time.perf_counter() - t0)
    return out


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)

    def _f(a):
        if not _in_trace(a):
            return a  # single-process eager: group of size 1
        if op in (ReduceOp.SUM, "sum"):
            return lax.psum(a, axis)
        if op in (ReduceOp.MAX, "max"):
            return lax.pmax(a, axis)
        if op in (ReduceOp.MIN, "min"):
            return lax.pmin(a, axis)
        if op in (ReduceOp.AVG, "avg"):
            return lax.pmean(a, axis)
        if op in (ReduceOp.PROD, "prod"):
            # sign/magnitude decomposition: log/exp alone breaks on
            # zeros and negatives
            mag = jnp.exp(lax.psum(jnp.log(jnp.maximum(jnp.abs(a), 1e-38)),
                                   axis))
            neg = lax.psum((a < 0).astype(jnp.int32), axis)
            has_zero = lax.pmax((a == 0).astype(jnp.int32), axis)
            sign = jnp.where(neg % 2 == 1, -1.0, 1.0).astype(a.dtype)
            return jnp.where(has_zero == 1, jnp.zeros_like(mag),
                             sign * mag.astype(a.dtype))
        raise ValueError(f"unknown op {op}")
    out = _apply_collective(_f, tensor, "all_reduce")
    tensor._set_array(out._array)
    return tensor


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """paddle signature: all_gather(tensor_list, tensor). Traced form:
    pass tensor only, returns the gathered Tensor."""
    if tensor is None:
        tensor, tensor_list = tensor_list, None
    ax_name = _axis_of(group)

    def _f(a):
        if not _in_trace(a):
            return a[None] if tensor_list is not None else a
        return lax.all_gather(a, ax_name, axis=0)
    out = _apply_collective(_f, tensor, "all_gather")
    if tensor_list is not None:
        n = out.shape[0]
        from ..tensor.manipulation import unstack
        parts = unstack(out, axis=0)
        tensor_list.clear()
        tensor_list.extend(parts)
        return tensor_list
    return out


def all_gather_object(object_list, obj, group=None):
    object_list.clear()
    object_list.append(obj)
    return object_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    axis = _axis_of(group)

    def _f(a):
        if not _in_trace(a):
            return a
        # broadcast = select src's value: gather then index (XLA folds this)
        gathered = lax.all_gather(a, axis, axis=0)
        return gathered[src]
    out = _apply_collective(_f, tensor, "broadcast")
    tensor._set_array(out._array)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # On SPMD hardware reduce == all_reduce with result used on dst.
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if tensor_list is not None and not _in_trace(tensor._array):
        tensor._set_array(tensor_list[get_rank(group)]._array)
        return tensor

    def _f(a):
        if not _in_trace(a):
            return a
        idx = lax.axis_index(axis)
        n = lax.axis_size(axis)
        chunk = a.shape[0] // n
        return lax.dynamic_slice_in_dim(a, idx * chunk, chunk, axis=0)
    out = _apply_collective(_f, tensor, "scatter")
    tensor._set_array(out._array)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """Traced form: pass a single stacked Tensor [n_ranks, ...] and get the
    transposed-exchange result (the MoE dispatch primitive,
    reference: global_scatter_op.cc)."""
    axis = _axis_of(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from ..tensor.manipulation import stack, unstack
        stacked = stack(list(in_tensor_list), axis=0)
        out = alltoall(stacked, None, group, sync_op)
        parts = unstack(out, axis=0)
        if out_tensor_list is not None:
            out_tensor_list.clear()
            out_tensor_list.extend(parts)
            return out_tensor_list
        return parts

    def _f(a):
        if not _in_trace(a):
            return a
        return lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                              tiled=False)
    return _apply_collective(_f, in_tensor_list, "alltoall")


all_to_all = alltoall


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    axis = _axis_of(group)
    if tensor_list is not None and not _in_trace(tensor._array):
        from ..tensor.math import add_n
        tensor._set_array(add_n(list(tensor_list))._array)
        return tensor

    def _f(a):
        if not _in_trace(a):
            return a
        return lax.psum_scatter(a, axis, scatter_dimension=0, tiled=True)
    out = _apply_collective(_f, tensor, "reduce_scatter")
    return out


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send — traced form is a ppermute shift (PP pipelines use
    distributed.pipeline's ppermute helpers directly)."""
    axis = _axis_of(group)

    def _f(a):
        if not _in_trace(a):
            return a
        n = lax.axis_size(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]
        return lax.ppermute(a, axis, perm)
    return _apply_collective(_f, tensor, "send")


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


class stream:
    """paddle.distributed.communication.stream parity — on XLA there is one
    logical stream; these re-export the sync collectives."""
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    alltoall = staticmethod(alltoall)
    reduce_scatter = staticmethod(reduce_scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all: leading dim split across ranks
    (reference: python/paddle/distributed/communication/all_to_all.py
    alltoall_single). Equal splits only — unequal splits have no static
    shape and do not map to XLA collectives."""
    assert in_split_sizes is None and out_split_sizes is None, \
        "alltoall_single: only equal splits are supported on XLA " \
        "(unequal splits are not static-shape compatible)"
    axis = _axis_of(group)

    def _f(a):
        if not _in_trace(a):
            return a
        return lax.all_to_all(a, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    out = _apply_collective(_f, in_tensor, "alltoall_single")
    if out_tensor is not None:
        out_tensor._set_array(out._array)
        return out_tensor
    return out


class _CompletedTask:
    """Future-like handle for the isend/irecv API (XLA collectives are
    scheduled by the compiler; by the time python sees the result it is
    already ordered — reference: communication/batch_isend_irecv.py
    P2POp task semantics)."""

    def wait(self):
        return True

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    """reference: communication/send.py isend — returns a task."""
    send(tensor, dst, group)
    return _CompletedTask()


def irecv(tensor, src=0, group=None):
    """reference: communication/recv.py irecv."""
    recv(tensor, src, group)
    return _CompletedTask()


def get_backend(group=None):
    """reference: collective.py get_backend — the one backend here is XLA
    collectives over ICI/DCN."""
    return "XCCL"


def broadcast_object_list(object_list, src=0, group=None):
    """reference: broadcast_object_list — single-process eager facade:
    src's objects are already the local list (world of 1); multi-host
    object broadcast rides the TCPStore (store.set/wait) in the gang
    scripts."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """reference: scatter_object_list — world-of-1 facade: rank 0 keeps
    its slice."""
    if in_object_list:
        out_object_list.clear()
        out_object_list.append(in_object_list[get_rank(group) %
                                              len(in_object_list)])
    return out_object_list


def gloo_barrier():
    """reference: gloo_barrier — CPU-side barrier; maps to the device
    barrier (single-process) / store barrier in gang scripts."""
    barrier()


def gloo_release():
    """reference: gloo_release — nothing to free on this stack."""
