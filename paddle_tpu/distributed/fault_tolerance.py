"""Fault tolerance: crash-consistent checkpoint commits, preemption
handling, bounded retries.

Reference analog: fleet/elastic/manager.py keeps preempted jobs alive by
relaunching workers (the exit-code-101 contract ``fleet.elastic``
reproduces) — but relaunch only helps if the state a worker resumes from
is never the half-written casualty of the crash that triggered it. This
module supplies the durable half of that contract, for both checkpoint
backends (orbax in ``distributed.checkpoint``, pickle in
``framework.io``):

Commit protocol
    A save writes into a ``*.ptq-tmp`` sibling, fsyncs every payload
    file, records a manifest (file list + sizes + CRC32s + step +
    framework version) written atomically inside the temp dir, then
    publishes with a single atomic ``os.replace`` of the directory. The
    commit point IS the rename: readers (``is_committed`` /
    ``committed_steps`` / ``verify_dir``) only ever see directories that
    carry a complete manifest, so a kill at any instant leaves either
    the previous committed state or the new one — never a torn mix.

Preemption
    :class:`PreemptionHandler` turns SIGTERM/SIGINT into a latched flag;
    :class:`CheckpointManager` (and ``hapi.Model.fit``) check it at step
    boundaries, cut a final synchronous checkpoint, and exit with
    ``RELAUNCH_EXIT_CODE`` (101) so ``fleet.elastic.ElasticJob``
    respawns the gang without burning its restart budget.

Retries
    :func:`retry_with_backoff` — bounded attempts, exponential backoff,
    seeded jitter, injectable sleep/clock (the ``bench.py``
    ``_init_device_with_retries`` idiom) — shared by the TCPStore client
    and ``utils.download``.

Telemetry lands in the profiler metrics registry (``ckpt_save_seconds``,
``ckpt_bytes_total``, ``ckpt_restore_fallback_total``...) and in the
"Checkpoints" section of ``Profiler.summary_table()``.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
import time
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..testing.chaos import chaos_point

__all__ = [
    "RELAUNCH_EXIT_CODE", "MANIFEST_NAME", "TMP_SUFFIX", "OLD_SUFFIX",
    "CheckpointCorruptionError", "VersionSkewError", "write_manifest",
    "read_manifest", "is_committed", "verify_dir", "commit_dir",
    "recover_dir", "step_dir_name", "committed_steps",
    "latest_committed_step", "prune_steps", "pin_step", "unpin_step",
    "pinned_steps", "backoff_delays", "retry_with_backoff",
    "PreemptionHandler", "CheckpointManager", "record_save",
    "record_restore", "record_fallback", "summary_lines", "stats",
    "reset_stats",
]

# fleet.elastic.RELAUNCH_EXIT_CODE — "checkpoint saved, relaunch me for
# free". Duplicated (not imported) so this module stays import-light;
# equality is asserted by tests/test_fault_tolerance.py.
RELAUNCH_EXIT_CODE = 101

MANIFEST_NAME = "ptq_manifest.json"
TMP_SUFFIX = ".ptq-tmp"
OLD_SUFFIX = ".ptq-old"

_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint directory failed manifest verification."""


class VersionSkewError(RuntimeError):
    """A checkpoint's recorded framework version differs from the
    running one while version-sensitive state (per-rank RNG streams) is
    being restored. RNG algorithms are allowed to change between
    versions, so a silent restore could fork the dropout/data-aug
    streams; pass ``allow_version_skew=True`` to restore anyway."""


# ---------------------------------------------------------------------------
# durability primitives
# ---------------------------------------------------------------------------

def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    # directory fsync makes the rename itself durable; some filesystems
    # (and all of CI's tmpfs variants) refuse — durability is then the
    # mount's problem, not a correctness one
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _crc32(path: str, chunk: int = 1 << 20) -> int:
    c = 0
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            c = zlib.crc32(block, c)
    return c & 0xFFFFFFFF


def _payload_files(dirpath: str):
    """(relpath, abspath) for every file under dirpath, manifest excluded."""
    for base, _dirs, files in os.walk(dirpath):
        for fn in files:
            p = os.path.join(base, fn)
            rel = os.path.relpath(p, dirpath)
            if rel == MANIFEST_NAME:
                continue
            yield rel, p


def _framework_version() -> str:
    try:
        from ..version import full_version
        return full_version
    except Exception:
        return "unknown"


# ---------------------------------------------------------------------------
# manifest + commit
# ---------------------------------------------------------------------------

def write_manifest(dirpath: str, extra: Optional[dict] = None,
                   fsync: bool = True) -> dict:
    """Record every payload file's size+CRC32, fsync payloads, then write
    the manifest atomically (tmp + fsync + replace) inside ``dirpath``."""
    files = []
    total = 0
    for rel, p in sorted(_payload_files(dirpath)):
        st = os.stat(p)
        files.append({"path": rel, "bytes": st.st_size, "crc32": _crc32(p)})
        total += st.st_size
        if fsync:
            _fsync_file(p)
    man = {"format": 1, "framework_version": _framework_version(),
           "bytes_total": total, "files": files}
    if extra:
        man.update(extra)
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1, sort_keys=True)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, mpath)
    if fsync:
        _fsync_dir(dirpath)
    return man


def read_manifest(dirpath: str) -> Optional[dict]:
    """The manifest dict, or None when absent/unreadable (uncommitted)."""
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    return man if isinstance(man, dict) and "files" in man else None


def is_committed(dirpath: str) -> bool:
    """True iff ``dirpath`` is a checkpoint that finished its commit."""
    return os.path.isdir(dirpath) and read_manifest(dirpath) is not None


def verify_dir(dirpath: str, checksums: bool = True) -> dict:
    """Check every manifest entry (presence, size, CRC32); returns the
    manifest or raises :class:`CheckpointCorruptionError`."""
    man = read_manifest(dirpath)
    if man is None:
        raise CheckpointCorruptionError(
            f"checkpoint {dirpath!r} has no commit manifest "
            f"({MANIFEST_NAME}): the save never committed")
    for ent in man["files"]:
        p = os.path.join(dirpath, ent["path"])
        if not os.path.isfile(p):
            raise CheckpointCorruptionError(
                f"checkpoint {dirpath!r} is missing {ent['path']!r}")
        size = os.path.getsize(p)
        if size != ent["bytes"]:
            raise CheckpointCorruptionError(
                f"checkpoint {dirpath!r}: {ent['path']!r} is {size} bytes, "
                f"manifest says {ent['bytes']} (truncated write?)")
        if checksums and _crc32(p) != ent["crc32"]:
            raise CheckpointCorruptionError(
                f"checkpoint {dirpath!r}: {ent['path']!r} fails its CRC32 "
                f"(bit rot or torn write)")
    return man


def commit_dir(tmp_dir: str, final_dir: str, *, overwrite: bool = True,
               extra: Optional[dict] = None) -> dict:
    """Publish ``tmp_dir`` at ``final_dir`` crash-consistently.

    Order: manifest into tmp (durable) -> move any existing final aside
    -> atomic rename tmp->final (THE commit point) -> drop the old copy.
    A kill between any two steps leaves a state :func:`recover_dir` maps
    back to exactly one committed checkpoint. Under FLAGS_tpu_watchdog
    the whole protocol runs inside the ``ckpt.commit`` phase (a hung
    fsync on a dying disk produces a stack dump + incident within
    FLAGS_tpu_watchdog_ckpt_commit seconds).
    """
    from ..runtime import watchdog as _watchdog
    with _watchdog.phase("ckpt.commit"):
        man = write_manifest(tmp_dir, extra=extra)
        chaos_point("ft.commit.swap", step=(extra or {}).get("step"),
                    path=final_dir)
        old = final_dir + OLD_SUFFIX
        if os.path.exists(final_dir):
            if not overwrite:
                raise FileExistsError(final_dir)
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(final_dir, old)
        os.replace(tmp_dir, final_dir)
        _fsync_dir(os.path.dirname(final_dir) or ".")
        if os.path.exists(old):
            shutil.rmtree(old, ignore_errors=True)
    return man


def recover_dir(path: str) -> str:
    """Resolve ``path`` to its committed incarnation after any crash.

    - final committed: it wins; stray tmp/old copies are dropped.
    - final absent/uncommitted, tmp committed: the crash hit between the
      old copy moving aside and the publish rename — the temp copy is
      fully durable, so roll the commit forward.
    - otherwise, old copy present: roll back to it.
    """
    tmp, old = path + TMP_SUFFIX, path + OLD_SUFFIX
    if is_committed(path):
        for stray in (tmp, old):
            if os.path.exists(stray):
                shutil.rmtree(stray, ignore_errors=True)
        return path
    if is_committed(tmp):
        if os.path.exists(path):  # uncommitted husk loses to durable tmp
            shutil.rmtree(path)
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path) or ".")
        if os.path.exists(old):
            shutil.rmtree(old, ignore_errors=True)
        return path
    if is_committed(old):
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(old, path)
        _fsync_dir(os.path.dirname(path) or ".")
        return path
    if os.path.exists(path):
        raise CheckpointCorruptionError(
            f"checkpoint {path!r} exists but never committed (no "
            f"{MANIFEST_NAME}) and no recoverable copy is adjacent")
    raise FileNotFoundError(f"no committed checkpoint at {path!r}")


# ---------------------------------------------------------------------------
# step-directory layout (shared by orbax + pickle backends)
# ---------------------------------------------------------------------------

def step_dir_name(step: int) -> str:
    return f"step_{step:08d}"


def _parse_step(name: str) -> Optional[int]:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def committed_steps(root: str) -> List[int]:
    """Ascending steps whose directories finished their commit."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        return []
    out = []
    for d in os.listdir(root):
        s = _parse_step(d)
        if s is not None and is_committed(os.path.join(root, d)):
            out.append(s)
    return sorted(out)


def latest_committed_step(root: str) -> Optional[int]:
    steps = committed_steps(root)
    return steps[-1] if steps else None


# keep-anchor registry: steps an in-flight rewind or corruption
# fallback could still target. CheckpointManager.restore pins every step
# it successfully verifies+loads (the "last verified good" anchor), and
# prune_steps refuses to delete a pinned step even when newer saves push
# it out of the keep window.
_PINNED: Dict[str, set] = {}
_PINNED_LOCK = threading.Lock()


def pin_step(root: str, step: int):
    """Protect ``root/step_N`` from :func:`prune_steps` until unpinned."""
    with _PINNED_LOCK:
        _PINNED.setdefault(os.path.abspath(root), set()).add(int(step))


def unpin_step(root: str, step: Optional[int] = None):
    """Drop one pin (or every pin under ``root`` when step is None)."""
    with _PINNED_LOCK:
        pins = _PINNED.get(os.path.abspath(root))
        if pins is None:
            return
        if step is None:
            pins.clear()
        else:
            pins.discard(int(step))


def pinned_steps(root: str) -> set:
    with _PINNED_LOCK:
        return set(_PINNED.get(os.path.abspath(root), ()))


def prune_steps(root: str, keep: int,
                inflight: Iterable[int] = ()) -> List[int]:
    """Drop old committed steps, keeping the newest ``keep`` (0 = keep
    all). Never touches the latest committed step, pinned steps
    (:func:`pin_step` — the rewind/fallback keep-anchor), steps an async
    save is still writing, or their temp dirs; stale crash-leftover temp
    dirs ARE swept. Returns the steps removed."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        return []
    inflight = set(inflight)
    pinned = pinned_steps(root)
    removed = []
    steps = committed_steps(root)
    last = steps[-1] if steps else None
    victims = steps[:-keep] if keep else []
    for s in victims:
        if s in inflight or s == last or s in pinned:
            continue
        shutil.rmtree(os.path.join(root, step_dir_name(s)),
                      ignore_errors=True)
        removed.append(s)
    for d in os.listdir(root):
        base, sep, _rest = d.partition(TMP_SUFFIX)
        if not sep:
            continue
        s = _parse_step(base)
        if s is not None and s in inflight:
            continue  # an async save is still streaming into it
        shutil.rmtree(os.path.join(root, d), ignore_errors=True)
    return removed


# ---------------------------------------------------------------------------
# bounded retries with exponential backoff + jitter
# ---------------------------------------------------------------------------

def backoff_delays(attempts: int, base: float = 0.05, factor: float = 2.0,
                   max_delay: float = 2.0, jitter: float = 0.25,
                   rng=None):
    """Yield the ``attempts - 1`` sleeps between attempts. Jitter scales
    each delay by [1, 1+jitter) drawn from ``rng`` (seed it for
    deterministic schedules in tests)."""
    if rng is None:
        import random
        rng = random.Random()
    d = base
    for _ in range(max(0, attempts - 1)):
        j = 1.0 + jitter * rng.random() if jitter else 1.0
        yield min(d, max_delay) * j
        d *= factor


def retry_with_backoff(fn: Callable[[], Any], *,
                       retryable: Tuple[type, ...] = (ConnectionError,
                                                     OSError),
                       give_up: Tuple[type, ...] = (),
                       attempts: int = 4, base_delay: float = 0.05,
                       factor: float = 2.0, max_delay: float = 2.0,
                       jitter: float = 0.25, sleep=time.sleep, rng=None,
                       on_retry: Optional[Callable] = None,
                       describe: str = ""):
    """Call ``fn`` up to ``attempts`` times; transient failures
    (``retryable`` minus ``give_up``) back off exponentially with jitter
    before the next try, non-transient ones raise immediately.
    ``sleep``/``rng`` are injectable so tests assert real schedules
    without real waiting (the ``bench._init_device_with_retries``
    idiom). ``on_retry(attempt, exc, delay)`` observes each backoff."""
    delays = backoff_delays(attempts, base=base_delay, factor=factor,
                            max_delay=max_delay, jitter=jitter, rng=rng)
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except give_up:
            raise
        except retryable as e:
            delay = next(delays, None)
            if delay is None:
                raise
            if on_retry is not None:
                on_retry(attempt, e, delay)
            _bump("retries")
            sleep(delay)


# ---------------------------------------------------------------------------
# telemetry (metrics registry + Profiler "Checkpoints" section)
# ---------------------------------------------------------------------------

def _new_stats() -> Dict[str, Any]:
    return {"saves": 0, "bytes": 0, "last_save_s": 0.0, "last_step": None,
            "restores": 0, "fallbacks": 0, "retries": 0,
            "preemption_armed": False, "preemption_requested": False,
            "preempt_exits": 0}


_STATS = _new_stats()
_STATS_LOCK = threading.Lock()


def _bump(key: str, amount=1):
    with _STATS_LOCK:
        _STATS[key] += amount


def _metrics():
    from ..profiler import metrics
    return metrics


def record_save(seconds: float, bytes_total: int,
                step: Optional[int] = None):
    with _STATS_LOCK:
        _STATS["saves"] += 1
        _STATS["bytes"] += bytes_total
        _STATS["last_save_s"] = seconds
        if step is not None:
            _STATS["last_step"] = step
    m = _metrics()
    if not m.enabled():
        return
    m.histogram("ckpt_save_seconds",
                "Checkpoint save+commit wall time").observe(seconds)
    m.counter("ckpt_bytes_total",
              "Bytes committed to checkpoints").inc(bytes_total)
    m.counter("ckpt_saves_total", "Committed checkpoint saves").inc()
    if step is not None:
        m.gauge("ckpt_last_committed_step",
                "Newest committed checkpoint step").set(step)


def record_restore(step: Optional[int] = None):
    with _STATS_LOCK:
        _STATS["restores"] += 1
    m = _metrics()
    if m.enabled():
        m.counter("ckpt_restores_total", "Checkpoint restores").inc()


def record_fallback(step: Optional[int] = None):
    """A committed-looking step was skipped during restore (corrupt or
    unreadable); the restore fell back to an older one."""
    with _STATS_LOCK:
        _STATS["fallbacks"] += 1
    m = _metrics()
    if m.enabled():
        m.counter("ckpt_restore_fallback_total",
                  "Restore attempts that skipped a corrupt/uncommitted "
                  "step and fell back to an older one").inc()


def stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_stats():
    with _STATS_LOCK:
        _STATS.clear()
        _STATS.update(_new_stats())


def summary_lines() -> list:
    """The "Checkpoints" block of ``Profiler.summary_table()``."""
    s = stats()
    mib = s["bytes"] / (1 << 20)
    lines = ["Checkpoints",
             f"  saves committed: {s['saves']}  ({mib:.1f} MiB total, "
             f"last {s['last_save_s'] * 1e3:.1f} ms)",
             f"  restores: {s['restores']}  "
             f"(corruption fallbacks: {s['fallbacks']})"]
    if s["last_step"] is not None:
        lines.append(f"  last committed step: {s['last_step']}")
    if s["retries"]:
        lines.append(f"  transient-error retries: {s['retries']}")
    if s["preemption_armed"]:
        state = "requested" if s["preemption_requested"] else "armed"
        lines.append(f"  preemption: {state}  "
                     f"(relaunch exits: {s['preempt_exits']})")
    return lines


# ---------------------------------------------------------------------------
# preemption handling
# ---------------------------------------------------------------------------

class PreemptionHandler:
    """Latch SIGTERM/SIGINT into a flag checked at step boundaries.

    The contract (fleet/elastic/manager.py's exit-101 protocol): on
    preemption notice, finish the current step, cut one final
    synchronous checkpoint, and exit ``RELAUNCH_EXIT_CODE`` so
    ``ElasticJob`` respawns the gang without consuming its restart
    budget. The signal handler itself only sets an Event — no I/O, no
    locks, async-signal-safe."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), *,
                 install: bool = True):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}
        self._installed = False
        if install:
            self.install()

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        with _STATS_LOCK:
            _STATS["preemption_armed"] = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, h in self._prev.items():
            signal.signal(s, h)
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame):
        self._event.set()
        with _STATS_LOCK:
            _STATS["preemption_requested"] = True

    def requested(self) -> bool:
        return self._event.is_set()

    def clear(self):
        self._event.clear()
        with _STATS_LOCK:
            _STATS["preemption_requested"] = False

    def exit_for_relaunch(self):
        """Exit asking the supervisor for a free relaunch."""
        _bump("preempt_exits")
        m = _metrics()
        if m.enabled():
            m.counter("ckpt_preempt_exits_total",
                      "Preemption exits requesting relaunch").inc()
        raise SystemExit(RELAUNCH_EXIT_CODE)

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Save-every-N / keep-K / auto-resume over the commit protocol.

    Backends: ``"orbax"`` for sharded jax pytrees (async-capable, rides
    ``distributed.checkpoint``), ``"pickle"`` for framework Tensor
    state_dicts (``framework.io``, always synchronous). Both lay out
    ``root/step_NNNNNNNN`` committed directories, so ``latest_step`` /
    ``restore`` semantics are identical.

    With ``preemption=True`` a :class:`PreemptionHandler` is armed and
    ``step_end`` honors it: final sync save, then ``SystemExit(101)``.

        mgr = CheckpointManager(root, save_interval_steps=50, keep=3)
        state, start = mgr.restore(target)   # (None, 0) on first launch
        for step in range(start, STEPS):
            state = train(state)
            mgr.step_end(step + 1, state)
    """

    def __init__(self, root: str, *, save_interval_steps: int = 1,
                 keep: int = 3, backend: str = "orbax", sync: bool = False,
                 preemption=False, state_file: str = "state.pdz",
                 track_rng: bool = True):
        if backend not in ("orbax", "pickle"):
            raise ValueError(f"backend must be 'orbax' or 'pickle', "
                             f"got {backend!r}")
        if save_interval_steps < 1:
            raise ValueError("save_interval_steps must be >= 1")
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.save_interval_steps = int(save_interval_steps)
        self.keep = int(keep)
        self.backend = backend
        self.sync = bool(sync) or backend == "pickle"
        self.state_file = state_file
        self.track_rng = bool(track_rng)
        self._data_obj = None
        self._owns_handler = preemption is True
        if preemption is True:
            self._preempt: Optional[PreemptionHandler] = PreemptionHandler()
        elif isinstance(preemption, PreemptionHandler):
            self._preempt = preemption
        else:
            self._preempt = None

    # -- data-pipeline tracking --------------------------------------------
    def attach_data(self, obj) -> "CheckpointManager":
        """Track a DataLoader / DistributedBatchSampler (anything with
        ``state_dict``/``load_state_dict``). Every save then embeds its
        state in the checkpoint manifest, and ``restore`` replays it —
        sample-exact resume, valid across a dp resize because sampler
        offsets are defined in global sample order."""
        if obj is not None and not hasattr(obj, "state_dict"):
            raise TypeError(
                f"attach_data needs an object with state_dict/"
                f"load_state_dict, got {type(obj).__name__}")
        self._data_obj = obj
        return self

    def _manifest_extra(self, step: int, state: Any = None) -> dict:
        """The topology/sharding/RNG/data-state block every committed
        checkpoint carries (reshard.manifest_extra; failures degrade to
        a bare {"step"} manifest rather than failing the save)."""
        extra: Dict[str, Any] = {"step": step}
        try:
            from .reshard import manifest_extra
            extra.update(manifest_extra(data=self._data_obj,
                                        rng=self.track_rng, state=state))
        except Exception as e:  # noqa: BLE001 — save must still commit
            import sys as _sys
            _sys.stderr.write(
                f"checkpoint: manifest extras unavailable ({e}); "
                f"saving step {step} without topology/rng state\n")
        return extra

    # -- queries ------------------------------------------------------------
    @property
    def preemption_handler(self) -> Optional[PreemptionHandler]:
        return self._preempt

    def preempted(self) -> bool:
        return self._preempt is not None and self._preempt.requested()

    def all_steps(self) -> List[int]:
        return committed_steps(self.root)

    def latest_step(self) -> Optional[int]:
        return latest_committed_step(self.root)

    def should_save(self, step: int) -> bool:
        return step > 0 and step % self.save_interval_steps == 0

    # -- save / restore -----------------------------------------------------
    def save(self, step: int, state: Any, *, sync: Optional[bool] = None):
        """Commit ``state`` as step ``step`` and prune old steps. The
        manifest carries the topology/sharding/RNG/data-pipeline block
        (:meth:`attach_data`, ``track_rng``) so the checkpoint restores
        onto a different world size with sample-exact data resume."""
        sync = self.sync if sync is None else sync
        extra = self._manifest_extra(step, state)
        if self.backend == "orbax":
            from . import checkpoint as dckpt
            dckpt.save_step(self.root, state, step, keep=self.keep,
                            sync=sync, extra=extra)
            return
        final = os.path.join(self.root, step_dir_name(step))
        tmp = final + TMP_SUFFIX
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        t0 = time.perf_counter()
        os.makedirs(tmp)
        from ..framework.io import save as fsave
        chaos_point("ckpt.save.pre", step=step, path=final)
        fsave(state, os.path.join(tmp, self.state_file))
        chaos_point("ckpt.commit.pre", step=step, path=final)
        man = commit_dir(tmp, final, extra=extra)
        chaos_point("ckpt.commit.post", step=step, path=final)
        record_save(time.perf_counter() - t0, man["bytes_total"], step=step)
        prune_steps(self.root, self.keep)

    def _apply_manifest_state(self, step: int, *, apply_data: bool,
                              apply_rng: bool, allow_version_skew: bool):
        man = read_manifest(os.path.join(self.root, step_dir_name(step)))
        if man is None:
            return
        from .reshard import apply_manifest_state
        apply_manifest_state(
            man, data=self._data_obj if apply_data else None,
            rng=apply_rng and self.track_rng,
            allow_version_skew=allow_version_skew)

    def restore(self, target: Any = None, step: Optional[int] = None, *,
                apply_data: bool = True, apply_rng: bool = True,
                allow_version_skew: bool = False) -> Tuple[Any, int]:
        """(state, step) from the newest loadable committed step —
        falling back past corrupt ones — or (None, 0) when the run is
        fresh. ``target`` (orbax backend) re-shards onto the current
        mesh.

        The restored step is pinned (:func:`pin_step`) as the
        last-verified-good anchor, so pruning can never delete the
        checkpoint an in-flight rewind or corruption fallback targets.
        When the manifest carries data-pipeline / RNG state it is
        replayed into the attached loader and the framework RNG
        (``apply_data``/``apply_rng``); RNG restore refuses a
        framework-version skew unless ``allow_version_skew=True``."""
        got: Optional[int] = None
        state: Any = None
        if self.backend == "orbax":
            from . import checkpoint as dckpt
            try:
                state, got = dckpt.load_step(self.root, target, step=step)
            except FileNotFoundError:
                return None, 0
        else:
            candidates = [step] if step is not None else \
                list(reversed(self.all_steps()))
            for s in candidates:
                d = os.path.join(self.root, step_dir_name(s))
                try:
                    verify_dir(d)
                    from ..framework.io import load as fload
                    state = fload(os.path.join(d, self.state_file))
                except (CheckpointCorruptionError, RuntimeError, OSError):
                    if step is not None:
                        raise
                    record_fallback(s)
                    continue
                got = s
                break
            if got is None:
                return None, 0
            record_restore(got)
        self._apply_manifest_state(
            got, apply_data=apply_data, apply_rng=apply_rng,
            allow_version_skew=allow_version_skew)
        # one anchor per root: the newest verified-good step
        unpin_step(self.root)
        pin_step(self.root, got)
        return state, got

    # -- train-loop hook ----------------------------------------------------
    def step_end(self, step: int, state: Any) -> bool:
        """Call once per completed step. Saves on the interval; on a
        pending preemption, cuts a final synchronous checkpoint and
        exits ``RELAUNCH_EXIT_CODE`` (raises SystemExit)."""
        if self.preempted():
            self.save(step, state, sync=True)
            self.wait()
            self._preempt.exit_for_relaunch()
        if self.should_save(step):
            self.save(step, state)
            return True
        return False

    def wait(self):
        """Block until every in-flight async save has committed."""
        if self.backend == "orbax":
            from . import checkpoint as dckpt
            dckpt.wait_until_finished()

    def close(self):
        self.wait()
        if self._owns_handler and self._preempt is not None:
            self._preempt.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
