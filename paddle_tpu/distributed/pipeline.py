"""Pipeline parallelism over the 'pp' mesh axis: GPipe + 1F1B schedules.

Reference analog: fleet/meta_parallel/pipeline_parallel.py:31
(PipelineParallel.train_batch) and :228 (_forward_backward_pipeline — the
1F1B steady state over NCCL p2p send/recv with SendRecvMeta handshakes)
and pp_layers.py:209 (PipelineLayer segmenting python Layers per stage).

TPU-native: the layer stack is an array axis sharded over 'pp'; the
schedule is a lax.scan whose per-step stage handoff is ONE lax.ppermute
over the pp axis inside shard_map — XLA lowers it to ICI neighbor DMA.
Backward needs no hand-written 1B schedule: jax.grad transposes the scan +
ppermute into the reverse pipeline automatically (the whole
p2p_communication.py module collapses into the transpose rule).

Bubble math matches GPipe: T = n_micro + pp - 1 steps, bubble fraction
(pp-1)/T. Invalid (bubble) steps compute garbage that is masked out of the
collected outputs — wasted FLOPs equal to the bubble, same as the
reference's idle stages.
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..profiler import trace as _trace

__all__ = ["pipeline_forward", "pipeline_loss_fn",
           "pipeline_1f1b_value_and_grad",
           "pipeline_interleaved_forward", "pipeline_interleaved_loss_fn"]


def pipeline_forward(cfg, mesh, n_micro, params, ids, cp_axis=None):
    """ids -> (hidden_states [B,S,H], aux) with the decoder stack pipelined
    over 'pp'. Embedding and head stay in the GSPMD (auto) region.

    cp_axis: also shard the SEQUENCE over this mesh axis inside the
    pipeline region and run axis-level ring attention per stage —
    context parallelism composed with pipeline parallelism (the
    long-context regime the reference never shipped: each stage holds
    S/n_sp of every microbatch's activations and rotates K/V blocks
    around the sp ring while activations hop the pp ring)."""
    from ..models.llama import _rope_tables, run_layer_stack

    B, S = ids.shape
    sin, cos = _rope_tables(cfg, S)
    x = jnp.take(params["embed"], ids, axis=0)         # [B, S, H]
    assert B % n_micro == 0, (B, n_micro)
    mb = B // n_micro
    x_mb = x.reshape(n_micro, mb, S, x.shape[-1])
    layers = params["layers"]

    def stage_body(layers_local, x_stack, sin_, cos_):
        n_stages = lax.axis_size("pp")
        stage = lax.axis_index("pp")

        def step(carry, t):
            state, outputs, aux = carry
            idx0 = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(stage == 0, x_stack[idx0], state)
            out, a = run_layer_stack(cfg, layers_local, inp, sin_, cos_,
                                     cp_axis=cp_axis,
                                     cp_axis_level=cp_axis is not None)
            out_idx = t - (n_stages - 1)
            valid_out = (stage == n_stages - 1) & (out_idx >= 0)
            upd = lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(out_idx, 0, n_micro - 1), 0)
            outputs = jnp.where(valid_out, upd, outputs)
            valid_compute = (t >= stage) & (t < stage + n_micro)
            aux = aux + jnp.where(valid_compute, a, 0.0)
            state = lax.ppermute(
                out, "pp",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (state, outputs, aux), None

        carry0 = (jnp.zeros_like(x_stack[0]), jnp.zeros_like(x_stack),
                  jnp.zeros((), jnp.float32))
        (state, outputs, aux), _ = lax.scan(
            step, carry0, jnp.arange(n_micro + n_stages - 1))
        # replicate the last stage's result across pp (loss/head computed
        # in the auto region). aux: stages hold disjoint layer slices
        # (sum over pp), microbatches each contribute a full-batch-mean
        # quantity (divide by n_micro to match loss_fn/1F1B), and cp
        # shards each hold a token-normalized mean (pmean over sp, not
        # psum — a sum would scale the load-balance loss by n_sp)
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), "pp")
        aux = lax.psum(aux, "pp") / n_micro
        if cp_axis is not None:
            aux = lax.pmean(aux, cp_axis)
        return outputs, aux

    layer_manual_specs = jax.tree_util.tree_map(lambda a: P("pp"), layers)
    if cp_axis is None:
        x_spec, rope_spec, axes = P(), P(), {"pp"}
    else:
        # sequence dim sharded over the cp axis; rope tables slice along
        # S so each shard sees its own absolute positions
        x_spec = P(None, None, cp_axis, None)
        rope_spec = P(cp_axis, None)
        axes = {"pp", cp_axis}
    outputs, aux = jax.shard_map(
        stage_body, mesh=mesh,
        in_specs=(layer_manual_specs, x_spec, rope_spec, rope_spec),
        out_specs=(x_spec, P()),
        axis_names=axes, check_vma=False)(layers, x_mb, sin, cos)
    h = outputs.reshape(B, S, x.shape[-1])
    return h, aux


def _head_loss(cfg, params, h, labels, aux):
    """Shared norm/lm_head/CE epilogue for every pipelined forward."""
    from ..models.llama import _rms_norm

    h = _rms_norm(h, params["norm_f"], cfg.rms_norm_eps)
    logits = (h @ params["lm_head"]).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    ce = jnp.mean(lse - tgt)
    return ce + 0.01 * aux, ce


def pipeline_loss_fn(cfg, mesh, n_micro, params, batch, cp_axis=None):
    """Full pipelined loss (used by models.llama.build_train_step)."""
    h, aux = pipeline_forward(cfg, mesh, n_micro, params,
                              batch["input_ids"], cp_axis=cp_axis)
    return _head_loss(cfg, params, h, batch["labels"], aux)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------

def pipeline_1f1b_value_and_grad(cfg, mesh, n_micro, params, batch,
                                 overlap=False):
    """Hand-scheduled 1F1B: returns (loss, ce, grads) directly.

    Reference analog: pipeline_parallel.py:228 (_forward_backward_pipeline
    — warmup forwards, steady 1F1B, cooldown backwards, capping in-flight
    activations at O(pp) instead of GPipe's O(n_micro)).

    TPU-native: one lax.scan of T ticks inside shard_map. Per tick every
    stage runs one forward unit (activation handed to the next stage by
    ppermute) and one backward unit (gradient handed to the previous
    stage by the reverse ppermute). The backward unit re-derives its vjp
    from a ring buffer of saved *stage inputs* — activation
    recomputation, so saved state per stage is O(pp) microbatch inputs
    regardless of n_micro, while grad-of-GPipe keeps residuals for every
    scan step. jax.grad's scan transpose is replaced by explicit
    per-unit jax.vjp, so this function computes its own grads (it is not
    meant to be differentiated).

    Two schedules (arithmetic shared with ``distributed.overlap`` so the
    static simulator and this kernel cannot drift):

    * ``overlap=False`` (lockstep): F(s,m) at tick s+m, B(s,m) at tick
      2*pp-1-s+m, T = n_micro + 2*pp - 1. The ppermute at the end of
      each tick feeds the consuming compute of the very next tick —
      every stage-boundary transfer serializes against compute.
    * ``overlap=True`` (double-buffered p2p): F(s,m) at tick 2s+m,
      B(s,m) at tick 4*(pp-1)+1-2s+m, T = n_micro + 4*pp - 3. Each
      stage keeps send/recv edge buffers in the carry and issues both
      ppermutes at the *top* of the tick on values computed a full tick
      earlier, so within any tick the transfers have no data dependence
      on that tick's forward/backward units — XLA's latency-hiding
      scheduler overlaps the ICI hop with the matmuls. The price is a
      deeper warmup (2 ticks/stage) and a 4*pp ring buffer; per-edge
      numerics are identical (same units, same accumulation order).

    The CE head runs per-microbatch inside the last stage's backward unit
    (its vjp seeds the gradient chain). The embedding lives inside the
    manual region too: stage 0 looks its microbatch up per forward unit
    (ids are int32 — tiny) and accumulates d_embed as a param-sized [V,H]
    carry per backward unit, so no O(B*S*H) activation or gradient stack
    is ever materialized — per-stage live state really is the ring
    buffer plus param-sized accumulators.
    """
    from ..models.llama import _rope_tables, _rms_norm, run_layer_stack
    from .overlap import schedule_constants

    # host-side build marker (the scan body itself is opaque to the
    # flight recorder — measured overlap comes from the recorded
    # schedule, see trace.record_pipeline_schedule)
    _trace.event("pipeline/build", kind="pipeline_build",
                 pp=int(mesh.shape["pp"]), n_micro=int(n_micro),
                 overlap=bool(overlap))

    ids, labels = batch["input_ids"], batch["labels"]
    B, S = ids.shape
    H = params["embed"].shape[1]
    sin, cos = _rope_tables(cfg, S)
    assert B % n_micro == 0, (B, n_micro)
    mb = B // n_micro
    ids_mb = ids.reshape(n_micro, mb, S)
    lab_mb = labels.reshape(n_micro, mb, S)
    layers = params["layers"]
    inv_nm = 1.0 / n_micro

    def stage_body(layers_local, embed_w, ids_stack, lab_stack, norm_w,
                   head_w, sin_, cos_):
        pp = lax.axis_size("pp")
        stage = lax.axis_index("pp")
        is_last = stage == pp - 1
        # pp is static under shard_map; T/BUF shared with the simulator
        consts = schedule_constants(int(pp), n_micro, overlap=overlap)
        BUF, T = consts["BUF"], consts["T"]

        def stage_fwd(ll, xin):
            return run_layer_stack(cfg, ll, xin, sin_, cos_)  # (y, aux)

        def head_ce(nw, hw, y, lab):
            h = _rms_norm(y, nw, cfg.rms_norm_eps)
            logits = (h @ hw).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, lab[..., None], -1)[..., 0]
            return jnp.mean(lse - tgt)

        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]

        def tick(carry, t):
            (fwd_state, bwd_state, fwd_recv, bwd_recv, xs_buf, dlayers,
             dembed, dnorm, dhead, ce_sum, aux_sum) = carry

            if overlap:
                # p2p issued FIRST, on edge values computed a full tick
                # earlier: no data dependence on this tick's compute, so
                # the collective-permute rides under the matmuls below.
                # fwd_state/bwd_state hold last tick's outputs (pending
                # send); fwd_recv/bwd_recv hold what arrived last tick
                # (consumed this tick).
                recv_f = lax.ppermute(fwd_state, "pp", fwd_perm)
                recv_b = lax.ppermute(bwd_state, "pp", bwd_perm)
                fwd_in, bwd_in = fwd_recv, bwd_recv
                fm = t - 2 * stage
                bm = t - (4 * (pp - 1) + 1 - 2 * stage)
            else:
                fwd_in, bwd_in = fwd_state, bwd_state
                fm = t - stage                      # F(s, m) at t = s + m
                bm = t - (2 * pp - 1 - stage)       # B(s, m)

            # ---- forward unit
            do_f = (fm >= 0) & (fm < n_micro)
            fidx = jnp.clip(fm, 0, n_micro - 1)
            x_emb = jnp.take(embed_w, ids_stack[fidx], axis=0)
            x_in = jnp.where(stage == 0, x_emb, fwd_in)
            y, _ = stage_fwd(layers_local, x_in)
            xs_upd = lax.dynamic_update_index_in_dim(
                xs_buf, x_in, fm % BUF, 0)
            xs_buf = jnp.where(do_f, xs_upd, xs_buf)

            # ---- backward unit
            do_b = (bm >= 0) & (bm < n_micro)
            bidx = jnp.clip(bm, 0, n_micro - 1)
            x_saved = xs_buf[bm % BUF]
            (y_b, aux_b), stage_vjp = jax.vjp(
                stage_fwd, layers_local, x_saved)
            ce_m, head_vjp = jax.vjp(
                lambda nw, hw, yy: head_ce(nw, hw, yy, lab_stack[bidx]),
                norm_w, head_w, y_b)
            dnorm_m, dhead_m, g_last = head_vjp(jnp.float32(inv_nm))
            g_in = jnp.where(is_last, g_last, bwd_in)
            dlayers_m, dx_m = stage_vjp(
                (g_in, jnp.asarray(0.01 * inv_nm, aux_b.dtype)))

            mask_b = do_b
            dlayers = jax.tree_util.tree_map(
                lambda acc, d: acc + jnp.where(mask_b, d, 0),
                dlayers, dlayers_m)
            mask_last = mask_b & is_last
            dnorm = dnorm + jnp.where(mask_last, dnorm_m, 0)
            dhead = dhead + jnp.where(mask_last, dhead_m, 0)
            ce_sum = ce_sum + jnp.where(mask_last, ce_m * inv_nm, 0.0)
            aux_sum = aux_sum + jnp.where(mask_b, aux_b * inv_nm, 0.0)
            # embedding backward: param-sized scatter-add on stage 0 —
            # no [n_micro, mb, S, H] gradient stack in the carry
            demb_m = jnp.zeros_like(dembed).at[ids_stack[bidx]].add(
                dx_m.astype(dembed.dtype))
            dembed = dembed + jnp.where(mask_b & (stage == 0), demb_m, 0)

            if overlap:
                # this tick's outputs become next tick's sends; this
                # tick's arrivals are consumed the tick after
                fwd_state, fwd_recv = y, recv_f
                bwd_state, bwd_recv = dx_m, recv_b
            else:
                fwd_state = lax.ppermute(y, "pp", fwd_perm)
                bwd_state = lax.ppermute(dx_m, "pp", bwd_perm)

            return (fwd_state, bwd_state, fwd_recv, bwd_recv, xs_buf,
                    dlayers, dembed, dnorm, dhead, ce_sum, aux_sum), None

        z = jnp.zeros((mb, S, H), embed_w.dtype)
        carry0 = (
            z, z, z, z, jnp.zeros((BUF, mb, S, H), embed_w.dtype),
            jax.tree_util.tree_map(jnp.zeros_like, layers_local),
            jnp.zeros_like(embed_w),
            jnp.zeros_like(norm_w), jnp.zeros_like(head_w),
            jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (fwd_state, bwd_state, fwd_recv, bwd_recv, xs_buf, dlayers,
         dembed, dnorm, dhead, ce_sum, aux_sum), _ = lax.scan(
            tick, carry0, jnp.arange(T))

        # head/embed grads and the scalars live on one stage; psum
        # replicates them so out_specs can be P()
        dembed = lax.psum(dembed, "pp")
        dnorm = lax.psum(dnorm, "pp")
        dhead = lax.psum(dhead, "pp")
        ce_sum = lax.psum(ce_sum, "pp")
        aux_sum = lax.psum(aux_sum, "pp")
        return dlayers, dembed, dnorm, dhead, ce_sum, aux_sum

    layer_manual_specs = jax.tree_util.tree_map(lambda a: P("pp"), layers)
    dlayers, dembed, dnorm, dhead, ce, aux = jax.shard_map(
        stage_body, mesh=mesh,
        in_specs=(layer_manual_specs, P(), P(), P(), P(), P(), P(), P()),
        out_specs=(layer_manual_specs, P(), P(), P(), P(), P()),
        axis_names={"pp"}, check_vma=False)(
            layers, params["embed"], ids_mb, lab_mb, params["norm_f"],
            params["lm_head"], sin, cos)

    grads = {"embed": dembed, "layers": dlayers, "norm_f": dnorm,
             "lm_head": dhead}
    loss = ce + 0.01 * aux
    return loss, ce, grads


# ---------------------------------------------------------------------------
# interleaved (virtual-stage) schedule
# ---------------------------------------------------------------------------

def pipeline_interleaved_forward(cfg, mesh, n_micro, v, params, ids):
    """Circular interleaved pipeline: each device holds ``v`` layer
    chunks (virtual stages), cutting the bubble fraction from
    (pp-1)/(m+pp-1) to roughly (pp-1)/(v*m+pp-1).

    Reference analog: pipeline_parallel.py:461
    (_forward_backward_pipeline with virtual_pp_degree — the interleaved
    1F1B schedule over chunked PipelineLayer segments).

    TPU-native: global stage g = chunk*pp + device. Microbatches stream
    in groups of pp (the reference's n_micro % pp == 0 constraint, made
    exact as group size = pp) through ONE fused scan of
    T = n_micro*v + pp - 1 ticks: work index r = t - device decomposes
    into (group, chunk, micro), every device executes exactly one unit
    per tick, and the hand-off g -> g+1 is the same neighbor ppermute as
    GPipe — when device pp-1 wraps to device 0 the receiver just indexes
    its next chunk. The drain bubble is paid once per batch, giving the
    (pp-1)/(v*m + pp-1) fraction above. Backward is jax.grad's transpose
    of the scan, as in the GPipe path.
    """
    from ..models.llama import _rope_tables, run_layer_stack

    import numpy as np

    B, S = ids.shape
    sin, cos = _rope_tables(cfg, S)
    x = jnp.take(params["embed"], ids, axis=0)
    assert B % n_micro == 0, (B, n_micro)
    mb = B // n_micro
    H = x.shape[-1]
    layers = params["layers"]
    L = jax.tree_util.tree_leaves(layers)[0].shape[0]

    # device d's pp-shard is a CONTIGUOUS layer block, but global stage
    # g = c*pp + d must hold layer chunk g: permute chunks so local
    # position (d, c) carries global chunk c*pp + d (grad transposes the
    # gather back automatically)
    pp_deg = dict(zip(mesh.axis_names,
                      np.asarray(mesh.devices).shape))["pp"]
    n_chunks = pp_deg * v
    assert L % n_chunks == 0, (L, pp_deg, v)
    perm = jnp.asarray([c * pp_deg + d for d in range(pp_deg)
                        for c in range(v)])

    def _reorder(a):
        ck = a.reshape(n_chunks, a.shape[0] // n_chunks, *a.shape[1:])
        return ck[perm].reshape(a.shape)

    layers = jax.tree_util.tree_map(_reorder, layers)

    def stage_body(layers_local, x_stack, sin_, cos_):
        pp = lax.axis_size("pp")
        d = lax.axis_index("pp")
        assert n_micro % pp == 0, (n_micro, pp)
        k_groups = n_micro // pp
        # layers_local: [L/pp, ...] -> [v, L/(pp*v), ...] virtual chunks
        chunked = jax.tree_util.tree_map(
            lambda a: a.reshape(v, a.shape[0] // v, *a.shape[1:]),
            layers_local)
        # ONE fused scan over all groups: work index r = t - d
        # decomposes as (group, chunk, micro) = (r//(v*pp), (r%(v*pp))
        # //pp, r%pp); groups stream back-to-back so the (pp-1)-tick
        # drain bubble is paid once per batch, not once per group
        T = k_groups * v * pp + pp - 1

        def tick(carry, t):
            state, outputs, aux = carry
            r = t - d
            active = (r >= 0) & (r < k_groups * v * pp)
            rr = jnp.clip(r, 0, k_groups * v * pp - 1)
            gi = rr // (v * pp)
            c = (rr % (v * pp)) // pp               # virtual chunk
            m_global = gi * pp + (rr % pp)          # micro index
            is_entry = (d == 0) & (c == 0)
            x_in = jnp.where(is_entry, x_stack[m_global], state)
            chunk_layers = jax.tree_util.tree_map(
                lambda a: a[c], chunked)
            y, a = run_layer_stack(cfg, chunk_layers, x_in, sin_, cos_)
            aux = aux + jnp.where(active, a, 0.0)
            is_exit = (d == pp - 1) & (c == v - 1) & active
            upd = lax.dynamic_update_index_in_dim(outputs, y, m_global, 0)
            outputs = jnp.where(is_exit, upd, outputs)
            state = lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return (state, outputs, aux), None

        carry0 = (jnp.zeros((mb, S, H), x_stack.dtype),
                  jnp.zeros((n_micro, mb, S, H), x_stack.dtype),
                  jnp.zeros((), jnp.float32))
        (_, outputs, aux), _ = lax.scan(tick, carry0, jnp.arange(T))
        outputs = lax.psum(
            jnp.where(d == pp - 1, outputs, jnp.zeros_like(outputs)),
            "pp")
        aux = lax.psum(aux, "pp")
        return outputs, aux

    layer_manual_specs = jax.tree_util.tree_map(lambda a: P("pp"), layers)
    x_mb = x.reshape(n_micro, mb, S, H)
    outputs, aux = jax.shard_map(
        stage_body, mesh=mesh,
        in_specs=(layer_manual_specs, P(), P(), P()),
        out_specs=(P(), P()),
        axis_names={"pp"}, check_vma=False)(layers, x_mb, sin, cos)
    h = outputs.reshape(B, S, H)
    return h, aux


def pipeline_interleaved_loss_fn(cfg, mesh, n_micro, v, params, batch):
    """Interleaved-schedule loss (build_train_step schedule
    "interleaved")."""
    h, aux = pipeline_interleaved_forward(cfg, mesh, n_micro, v, params,
                                          batch["input_ids"])
    return _head_loss(cfg, params, h, batch["labels"], aux)
