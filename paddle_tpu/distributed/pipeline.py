"""Pipeline parallelism — GPipe schedule over the 'pp' mesh axis.

Reference analog: fleet/meta_parallel/pipeline_parallel.py:31
(PipelineParallel.train_batch — 1F1B over NCCL p2p send/recv with
SendRecvMeta handshakes) and pp_layers.py:209 (PipelineLayer segmenting
python Layers per stage).

TPU-native: the layer stack is an array axis sharded over 'pp'; the
schedule is a lax.scan whose per-step stage handoff is ONE lax.ppermute
over the pp axis inside shard_map — XLA lowers it to ICI neighbor DMA.
Backward needs no hand-written 1B schedule: jax.grad transposes the scan +
ppermute into the reverse pipeline automatically (the whole
p2p_communication.py module collapses into the transpose rule).

Bubble math matches GPipe: T = n_micro + pp - 1 steps, bubble fraction
(pp-1)/T. Invalid (bubble) steps compute garbage that is masked out of the
collected outputs — wasted FLOPs equal to the bubble, same as the
reference's idle stages.
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_forward", "pipeline_loss_fn"]


def pipeline_forward(cfg, mesh, n_micro, params, ids):
    """ids -> (hidden_states [B,S,H], aux) with the decoder stack pipelined
    over 'pp'. Embedding and head stay in the GSPMD (auto) region."""
    from ..models.llama import _rope_tables, run_layer_stack

    B, S = ids.shape
    sin, cos = _rope_tables(cfg, S)
    x = jnp.take(params["embed"], ids, axis=0)         # [B, S, H]
    assert B % n_micro == 0, (B, n_micro)
    mb = B // n_micro
    x_mb = x.reshape(n_micro, mb, S, x.shape[-1])
    layers = params["layers"]

    def stage_body(layers_local, x_stack, sin_, cos_):
        n_stages = lax.axis_size("pp")
        stage = lax.axis_index("pp")

        def step(carry, t):
            state, outputs, aux = carry
            idx0 = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(stage == 0, x_stack[idx0], state)
            out, a = run_layer_stack(cfg, layers_local, inp, sin_, cos_)
            out_idx = t - (n_stages - 1)
            valid_out = (stage == n_stages - 1) & (out_idx >= 0)
            upd = lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(out_idx, 0, n_micro - 1), 0)
            outputs = jnp.where(valid_out, upd, outputs)
            valid_compute = (t >= stage) & (t < stage + n_micro)
            aux = aux + jnp.where(valid_compute, a, 0.0)
            state = lax.ppermute(
                out, "pp",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (state, outputs, aux), None

        carry0 = (jnp.zeros_like(x_stack[0]), jnp.zeros_like(x_stack),
                  jnp.zeros((), jnp.float32))
        (state, outputs, aux), _ = lax.scan(
            step, carry0, jnp.arange(n_micro + n_stages - 1))
        # replicate the last stage's result across pp (loss/head computed
        # in the auto region); scalar aux sums contributions of all stages
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), "pp")
        aux = lax.psum(aux, "pp")
        return outputs, aux

    layer_manual_specs = jax.tree_util.tree_map(lambda a: P("pp"), layers)
    outputs, aux = jax.shard_map(
        stage_body, mesh=mesh,
        in_specs=(layer_manual_specs, P(), P(), P()),
        out_specs=(P(), P()),
        axis_names={"pp"}, check_vma=False)(layers, x_mb, sin, cos)
    h = outputs.reshape(B, S, x.shape[-1])
    return h, aux


def pipeline_loss_fn(cfg, mesh, n_micro, params, batch):
    """Full pipelined loss (used by models.llama.build_train_step)."""
    from ..models.llama import _rms_norm

    ids, labels = batch["input_ids"], batch["labels"]
    h, aux = pipeline_forward(cfg, mesh, n_micro, params, ids)
    h = _rms_norm(h, params["norm_f"], cfg.rms_norm_eps)
    logits = (h @ params["lm_head"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    ce = -jnp.mean(ll)
    return ce + 0.01 * aux, ce
