"""paddle.distributed.io — distributed persistence helpers.

Reference analog: python/paddle/distributed/io.py
(save_persistables/load_persistables for PS trainers + is_persistable).

TPU-native: persistence rides framework.io's save/load (orbax handles
the genuinely distributed checkpoints in distributed/checkpoint.py);
these wrappers keep the reference's entry points for PS-style scripts.
"""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables", "is_persistable"]


def is_persistable(var) -> bool:
    """A parameter or buffer persists; activations don't. On this stack
    that is 'any named Tensor a Layer owns'."""
    from ..core.tensor import Tensor
    return isinstance(var, Tensor) and not getattr(
        var, "_is_temporary", False)


def save_persistables(executor, dirname, main_program=None,
                      filename=None):
    """Save a static Program's (or a Layer's) persistable state.
    ``executor`` is accepted for signature parity; state comes from the
    program bound by minimize()/run."""
    from ..framework.io import save

    prog = main_program
    if prog is None:
        from ..static.program import default_main_program
        prog = default_main_program()
    state = getattr(prog, "state_dict", lambda: {})()
    os.makedirs(dirname, exist_ok=True)
    save(state, os.path.join(dirname, filename or "persistables.pdparams"))


def load_persistables(executor, dirname, main_program=None,
                      filename=None):
    from ..framework.io import load

    path = os.path.join(dirname, filename or "persistables.pdparams")
    state = load(path)
    prog = main_program
    if prog is None:
        from ..static.program import default_main_program
        prog = default_main_program()
    setter = getattr(prog, "set_state_dict", None)
    if setter is not None:
        setter(state)
    return state
