"""Process launch utilities.

Reference analog: python/paddle/distributed/launch/ (python -m
paddle.distributed.launch, controllers/collective.py build_pod) and
paddle.distributed.spawn.

On TPU the unit of launch is one process per HOST (all local chips belong
to one jax client), so `spawn` with nprocs>1 on one host is only meaningful
for CPU-mesh testing. The pod launcher (per-rank logs, TCPStore rendezvous
env, gang restart) lives in distributed.launch.
"""
from __future__ import annotations

import multiprocessing as mp
import os

__all__ = ["spawn"]


def _spawn_target(fn, rank, nprocs, env, args):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs == 1:
        func(*args)
        return None
    ctx = mp.get_context("spawn")
    procs = []
    base_env = {k: v for k, v in os.environ.items()}
    for rank in range(nprocs):
        p = ctx.Process(target=_spawn_target,
                        args=(func, rank, nprocs, base_env, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode:
                raise RuntimeError(
                    f"spawned rank failed with exit code {p.exitcode}")
    return procs
