"""paddle.distributed parity surface — TPU-native (SURVEY.md §1 L6, §2.3).

NCCL ProcessGroups → named mesh axes; TCPStore/launch →
jax.distributed.initialize; collective ops → lax collectives under
shard_map/pjit; fleet 4-D hybrid topology → one jax Mesh.
"""
from .collective import (ReduceOp, Group, new_group, get_group, all_reduce,
                         all_gather, all_gather_object, broadcast, reduce,
                         scatter, alltoall, all_to_all, send, recv,
                         reduce_scatter, barrier, get_rank, get_world_size,
                         is_initialized, destroy_process_group, wait, stream)
from .parallel import (init_parallel_env, ParallelEnv, DataParallel)
from .mesh import (HybridTopology, init_mesh, get_mesh, set_mesh,
                   get_topology, ProcessMesh, PartitionSpec, NamedSharding)
from .shard import (shard_tensor, shard_op, shard_layer,
                    with_sharding_constraint, shard_params, replicate_params)
from .random import RNGStatesTracker, get_rng_state_tracker, \
    model_parallel_random_seed
from .recompute import recompute, recompute_sequential
from . import fleet
from . import sharding
from . import pipeline
from . import rpc
from . import auto_parallel
from .launch_utils import spawn, launch

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce",
    "all_gather", "all_gather_object", "broadcast", "reduce", "scatter",
    "alltoall", "all_to_all", "send", "recv", "reduce_scatter", "barrier",
    "get_rank", "get_world_size", "is_initialized", "destroy_process_group",
    "wait", "stream", "init_parallel_env", "ParallelEnv", "DataParallel",
    "HybridTopology", "init_mesh", "get_mesh", "set_mesh", "get_topology",
    "ProcessMesh", "PartitionSpec", "NamedSharding", "shard_tensor",
    "shard_op", "shard_layer", "with_sharding_constraint", "shard_params",
    "replicate_params", "RNGStatesTracker", "get_rng_state_tracker",
    "model_parallel_random_seed", "fleet", "sharding", "spawn", "launch",
    "recompute", "recompute_sequential", "pipeline", "rpc", "auto_parallel",
]
