"""paddle.distributed parity surface — TPU-native (SURVEY.md §1 L6, §2.3).

NCCL ProcessGroups → named mesh axes; TCPStore/launch →
jax.distributed.initialize; collective ops → lax collectives under
shard_map/pjit; fleet 4-D hybrid topology → one jax Mesh.
"""
from .collective import (ReduceOp, Group, new_group, get_group, all_reduce,
                         all_gather, all_gather_object, broadcast, reduce,
                         scatter, alltoall, all_to_all, alltoall_single,
                         send, recv, isend, irecv, reduce_scatter, barrier,
                         get_rank, get_world_size, get_backend,
                         is_initialized, destroy_process_group, wait,
                         stream, broadcast_object_list,
                         scatter_object_list, gloo_barrier, gloo_release)
from .ps_dataset import (InMemoryDataset, QueueDataset, CountFilterEntry,
                         ShowClickEntry, ProbabilityEntry, ParallelMode,
                         is_available)
from . import io
from .parallel import (init_parallel_env, shutdown, ParallelEnv,
                       DataParallel)
from .mesh import (HybridTopology, init_mesh, get_mesh, set_mesh,
                   get_topology, ProcessMesh, PartitionSpec, NamedSharding)
from .shard import (shard_tensor, shard_op, shard_layer,
                    with_sharding_constraint, shard_params, replicate_params)
from .random import RNGStatesTracker, get_rng_state_tracker, \
    model_parallel_random_seed
from .recompute import recompute, recompute_sequential
from . import fleet
from . import sharding
from . import checkpoint
from . import fault_tolerance
from . import reshard
from .fault_tolerance import CheckpointManager, PreemptionHandler
from .reshard import restore_resharded
from . import pipeline
from . import overlap
from .plan import (Plan, PlanError, PlanCompilationError,
                   PlanVerificationError)
from . import rpc
from . import auto_parallel
from .launch_utils import spawn
from . import launch
from . import gang
from . import ps

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce",
    "all_gather", "all_gather_object", "broadcast", "reduce", "scatter",
    "alltoall", "all_to_all", "alltoall_single", "send", "recv", "isend",
    "irecv", "reduce_scatter", "barrier", "get_backend",
    "gloo_init_parallel_env", "shutdown_process_group", "split",
    "get_rank", "get_world_size", "is_initialized", "destroy_process_group",
    "wait", "stream", "init_parallel_env", "shutdown", "ParallelEnv",
    "DataParallel", "broadcast_object_list", "scatter_object_list",
    "gloo_barrier", "gloo_release", "InMemoryDataset", "QueueDataset",
    "CountFilterEntry", "ShowClickEntry", "ProbabilityEntry",
    "ParallelMode", "is_available", "io",
    "HybridTopology", "init_mesh", "get_mesh", "set_mesh", "get_topology",
    "ProcessMesh", "PartitionSpec", "NamedSharding", "shard_tensor",
    "shard_op", "shard_layer", "with_sharding_constraint", "shard_params",
    "replicate_params", "RNGStatesTracker", "get_rng_state_tracker",
    "model_parallel_random_seed", "fleet", "sharding", "spawn", "launch",
    "recompute", "recompute_sequential", "pipeline", "rpc", "auto_parallel",
    "fault_tolerance", "CheckpointManager", "PreemptionHandler",
    "reshard", "restore_resharded",
    "overlap", "Plan", "PlanError", "PlanCompilationError",
    "PlanVerificationError", "gang",
]


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: parallel.py gloo_init_parallel_env — CPU-only bootstrap;
    the XLA build has one bootstrap path (init_parallel_env)."""
    return init_parallel_env()


def shutdown_process_group(group=None):
    """reference: collective shutdown_process_group."""
    return destroy_process_group(group)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: fleet/layers/mpu/mp_ops.py split:653 — builds a
    row/column-parallel linear or vocab-parallel embedding. Delegates to
    the TP layer library (fleet mp_layers)."""
    from . import fleet as _fleet
    if operation == "linear":
        if axis == 1:
            layer = _fleet.ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                gather_output=gather_out)
        else:
            layer = _fleet.RowParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False)
        return layer(x)
    if operation == "embedding":
        layer = _fleet.VocabParallelEmbedding(size[0], size[1],
                                              weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"split: unknown operation {operation!r}")
