"""paddle.distributed.rpc parity — a minimal peer-to-peer RPC layer.

Reference analog: python/paddle/distributed/rpc/rpc.py (init_rpc:73,
rpc_sync:141, rpc_async:179, shutdown:270, get_worker_info:299) backed by a
brpc `RpcAgent` (paddle/fluid/distributed/rpc/rpc_agent.h).

TPU-native design: TPU training traffic all rides XLA collectives, so RPC
here serves the same *control-plane* role it does in the reference (actor
coordination, parameter pulls, custom protocols) — not tensor transport.
Implementation: each worker runs a `multiprocessing.connection.Listener`
service thread; the rendezvous/endpoint directory is the same TCPStore the
collective bootstrap uses (csrc/tcp_store.cc). Calls pickle (fn, args,
kwargs), results come back pickled; `rpc_async` returns a
`concurrent.futures.Future` ("FutureWrapper" in the reference).
"""
from __future__ import annotations

import logging
import os
import pickle
import socket
import threading
import traceback
from collections import namedtuple
from concurrent.futures import Future, ThreadPoolExecutor
from multiprocessing.connection import Client, Listener
from typing import Dict, Optional

from .store import TCPStore

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "refresh_worker_infos", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info",
           "WorkerInfo"]

_log = logging.getLogger(__name__)

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = -1

_state: Dict[str, object] = {
    "listener": None, "thread": None, "pool": None, "client_pool": None,
    "store": None, "infos": {}, "self": None, "running": False,
}
_AUTHKEY = b"paddle_tpu_rpc"


def _host_ip(master_host):
    """The address peers should dial for this worker. For a loopback
    master everything is on one machine; otherwise use the interface that
    routes toward the master (multi-host pods)."""
    if master_host in ("127.0.0.1", "localhost", "::1"):
        return "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect((master_host, 9))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def _serve_conn(conn):
    try:
        while True:
            try:
                msg = conn.recv_bytes()
            except (EOFError, OSError):
                return
            fn, args, kwargs = pickle.loads(msg)
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # noqa: BLE001 — ship the error back
                result = (False, "".join(traceback.format_exception(e)))
            conn.send_bytes(pickle.dumps(result))
    finally:
        conn.close()


def _serve(listener, pool):
    while _state["running"]:
        try:
            conn = listener.accept()
        except OSError:
            return
        pool.submit(_serve_conn, conn)


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """reference: rpc.py:73. Starts the worker service, registers
    (name, rank, ip, port) in the master TCPStore, and blocks until all
    `world_size` workers registered."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:29431")
    host, port = master_endpoint.rsplit(":", 1)

    from ..core import native
    if world_size > 1 and not native.available():
        raise RuntimeError(
            "init_rpc with world_size > 1 requires the native TCPStore "
            "(csrc/tcp_store.cc): the pure-python fallback store is "
            "per-process, so cross-process rendezvous would hang. "
            "Build it with `make -C csrc`.")

    my_ip = _host_ip(host)
    bind_addr = "127.0.0.1" if my_ip == "127.0.0.1" else "0.0.0.0"
    listener = Listener((bind_addr, 0), authkey=_AUTHKEY)
    my_port = listener.address[1]
    pool = ThreadPoolExecutor(max_workers=8,
                              thread_name_prefix="rpc_worker")
    # outgoing calls get their own pool: an inbound handler occupies a
    # `pool` thread for its connection's lifetime, so sharing one pool lets
    # inbound traffic starve (or, with nested RPC, deadlock) outgoing calls
    client_pool = ThreadPoolExecutor(max_workers=8,
                                     thread_name_prefix="rpc_client")
    _state.update(listener=listener, pool=pool, client_pool=client_pool,
                  running=True)
    th = threading.Thread(target=_serve, args=(listener, pool), daemon=True)
    th.start()
    _state["thread"] = th

    store = TCPStore(host, int(port), is_master=(rank == 0),
                     world_size=world_size)
    _state["store"] = store
    if rank == 0:  # clear stale keys from a previous init on this endpoint
        for r in range(world_size):
            store.delete_key(f"rpc/{r}")
        store.delete_key("rpc/shutdown")
        store.delete_key("rpc/shutdown_ack")
        store.set("rpc/ready", b"1")
    else:
        store.wait("rpc/ready")
    me = WorkerInfo(name, rank, my_ip, my_port)
    store.set(f"rpc/{rank}", pickle.dumps(tuple(me)))
    infos = {}
    for r in range(world_size):
        info = WorkerInfo(*pickle.loads(store.wait(f"rpc/{r}")))
        if info.name in {i.name for i in infos.values()}:
            raise ValueError(f"worker name {info.name!r} is not unique")
        infos[info.name] = info
    _state["infos"] = infos
    _state["self"] = me


def _invoke(to, fn, args, kwargs):
    info = _state["infos"].get(to)
    if info is None:
        raise RuntimeError(f"unknown rpc worker {to!r}; "
                           f"known: {sorted(_state['infos'])}")
    conn = Client((info.ip, info.port), authkey=_AUTHKEY)
    try:
        conn.send_bytes(pickle.dumps((fn, args or (), kwargs or {})))
        ok, payload = pickle.loads(conn.recv_bytes())
    finally:
        conn.close()
    if not ok:
        raise RuntimeError(f"rpc to {to!r} failed remotely:\n{payload}")
    return payload


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """reference: rpc.py:141 — blocking remote call."""
    fut = rpc_async(to, fn, args, kwargs, timeout)
    return fut.result(None if timeout in (None, -1) else timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """reference: rpc.py:179 — returns a Future with .wait()/.result()."""
    pool: ThreadPoolExecutor = _state["client_pool"]
    if pool is None:
        raise RuntimeError("init_rpc must be called first")
    fut: Future = pool.submit(_invoke, to, fn, args, kwargs)
    fut.wait = fut.result  # paddle's FutureWrapper API
    return fut


def shutdown():
    """reference: rpc.py:270 — barrier then stop serving."""
    if not _state["running"]:
        return
    store = _state["store"]
    world = len(_state["infos"])
    me = _state["self"]
    if store is not None and world:
        import time
        # phase 1: everyone arrives (no rank may stop serving before all
        # peers are past their last rpc call)
        n = store.add("rpc/shutdown", 1)
        while n < world:
            time.sleep(0.01)
            n = store.add("rpc/shutdown", 0)
        # phase 2: acks; the master (rank 0 hosts the store server) must
        # outlive every client's final store op, so it leaves last
        n = store.add("rpc/shutdown_ack", 1)
        if me is not None and me.rank == 0:
            while n < world:
                time.sleep(0.01)
                n = store.add("rpc/shutdown_ack", 0)
    _state["running"] = False
    try:
        _state["listener"].close()
    except (OSError, AttributeError) as e:
        # a listener that died mid-serve (or was never created) has
        # nothing left to close; keep tearing the rest down
        _log.debug("rpc shutdown: listener close failed: %s", e)
    if store is not None:
        try:
            store.close()
        except AttributeError:
            pass
    _state["pool"].shutdown(wait=False)
    _state["client_pool"].shutdown(wait=False)
    _state.update(listener=None, thread=None, pool=None, client_pool=None,
                  store=None, infos={}, self=None)


def refresh_worker_infos():
    """Re-read the endpoint directory from the master store.

    A worker that crashed and rejoined (init_rpc with its old name/rank)
    re-registers at a NEW (ip, port); peers holding the old endpoint
    would keep dialing the dead socket. Call this after the replacement
    has rejoined, then retry — the reference's brpc channels re-resolve
    PS endpoints the same way on server restart.
    """
    store = _state["store"]
    if store is None:
        raise RuntimeError("init_rpc must be called first")
    infos = {}
    for r in range(len(_state["infos"])):
        info = WorkerInfo(*pickle.loads(store.wait(f"rpc/{r}")))
        infos[info.name] = info
    _state["infos"] = infos
    return get_all_worker_infos()


def get_worker_info(name) -> Optional[WorkerInfo]:
    return _state["infos"].get(name)


def get_all_worker_infos():
    return sorted(_state["infos"].values(), key=lambda i: i.rank)


def get_current_worker_info() -> Optional[WorkerInfo]:
    return _state["self"]
