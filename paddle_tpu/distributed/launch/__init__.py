"""Distributed launcher: ``python -m paddle_tpu.distributed.launch``.

Reference analog: python/paddle/distributed/launch/main.py:18 (the
``launch`` module: Pod/Container job model in
launch/controllers/collective.py, per-rank log files, a watchdog that
tears the pod down when any rank dies) plus the restart half of
fleet/elastic/manager.py:126 (gang restart with a bounded retry budget).

TPU-native shape: the unit of launch is one worker per HOST (all local
chips belong to one jax client; in-host parallelism comes from the mesh,
not processes), so this launcher manages host-level workers. Rendezvous
env rides the native TCPStore (csrc/tcp_store.cc) served from the
launcher process: workers get PADDLE_MASTER / MASTER_ADDR / MASTER_PORT /
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_RESTART_COUNT, the same
contract init_parallel_env consumes. Worker stdout/stderr stream to
``<log_dir>/workerlog.<rank>``. Failure policy is gang semantics, like
the reference pod watchdog: one dead rank kills the pod, and the pod
restarts as a unit up to ``--max_restarts`` times.

Elastic mode (``--elastic``) supervises the pod with
fleet.elastic.ElasticJob: world-size scale events watched on the job's
TCPStore (``--scale`` operator CLI / ``request_scale``), the exit-101
cooperative relaunch protocol, and bounds via ``--min_nproc`` /
``--max_nproc`` — the reference ElasticManager's contract with the
TCPStore standing in for etcd.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["LocalJob", "main"]


class _Worker:
    def __init__(self, rank: int, proc: subprocess.Popen, log_path: str):
        self.rank = rank
        self.proc = proc
        self.log_path = log_path


class LocalJob:
    """A pod of nproc workers on this host with gang restart."""

    # sentinel _watch returns when a scale event interrupts the gang
    # (only ElasticJob's _check_rescale can trigger it)
    RESCALE_RC = -1001

    def __init__(self, script: str, script_args: List[str], nproc: int,
                 master: Optional[str] = None, log_dir: str = "log",
                 job_id: str = "default", max_restarts: int = 3,
                 use_module: bool = False,
                 heartbeat_timeout: Optional[float] = None):
        self.script = script
        self.script_args = script_args
        self.nproc = nproc
        self.log_dir = log_dir
        self.job_id = job_id
        self.max_restarts = max_restarts
        self.use_module = use_module
        self.heartbeat_timeout = heartbeat_timeout
        self.restart_count = 0
        self._store = None
        self._monitor = None
        if master:
            host, port = master.rsplit(":", 1)
            self.master_host, self.master_port = host, int(port)
        else:
            self.master_host, self.master_port = "127.0.0.1", 0

    def _start_store(self):
        from ..store import TCPStore
        self._store = TCPStore(self.master_host, self.master_port,
                               is_master=True, timeout=300)
        self.master_port = self._store.port
        if self.heartbeat_timeout:
            from ..fleet.elastic import HeartbeatMonitor
            self._monitor = HeartbeatMonitor(
                self._store, self.job_id, self.nproc,
                self.heartbeat_timeout)

    def _spawn_one(self, rank: int) -> _Worker:
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(self.nproc),
            "PADDLE_MASTER": f"{self.master_host}:{self.master_port}",
            "MASTER_ADDR": self.master_host,
            "MASTER_PORT": str(self.master_port),
            "PADDLE_JOB_ID": self.job_id,
            "PADDLE_RESTART_COUNT": str(self.restart_count),
        })
        os.makedirs(self.log_dir, exist_ok=True)
        log_path = os.path.join(self.log_dir, f"workerlog.{rank}")
        logf = open(log_path, "ab")
        cmd = [sys.executable]
        if self.use_module:
            cmd += ["-m", self.script]
        else:
            cmd += [self.script]
        cmd += self.script_args
        proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
        logf.close()
        return _Worker(rank, proc, log_path)

    def _kill_all(self, workers):
        for w in workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.time() + 5
        for w in workers:
            try:
                w.proc.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait()

    def run(self, poll_interval: float = 0.2) -> int:
        """Run to completion with gang restart; returns the exit code."""
        if self._store is None:
            self._start_store()
        while True:
            workers = [self._spawn_one(r) for r in range(self.nproc)]
            rc = self._watch(workers, poll_interval)
            if rc == 0:
                return 0
            if self.restart_count >= self.max_restarts:
                sys.stderr.write(
                    f"launch: pod failed rc={rc} after "
                    f"{self.restart_count} restarts (budget "
                    f"{self.max_restarts}); giving up\n")
                return rc
            self.restart_count += 1
            sys.stderr.write(
                f"launch: worker failure rc={rc}; gang restart "
                f"{self.restart_count}/{self.max_restarts}\n")

    def _watch(self, workers, poll_interval) -> int:
        """Block until all workers exit 0 (return 0) or any fails
        (kill the gang, return its rc)."""
        if self._monitor is not None:
            self._monitor.reset()
        try:
            while True:
                alive = False
                for w in workers:
                    rc = w.proc.poll()
                    if rc is None:
                        alive = True
                    elif rc != 0:
                        sys.stderr.write(
                            f"launch: rank {w.rank} exited rc={rc} "
                            f"(log: {w.log_path})\n")
                        self._kill_all(workers)
                        return rc
                if not alive:
                    return 0
                if self._check_rescale():
                    self._kill_all(workers)
                    return self.RESCALE_RC
                if self._monitor is not None:
                    stale = self._monitor.stale_ranks(self.restart_count)
                    stale = [r for r in stale
                             if workers[r].proc.poll() is None]
                    if stale:
                        sys.stderr.write(
                            f"launch: ranks {stale} heartbeat-stale "
                            f"(> {self.heartbeat_timeout}s): "
                            "declaring hung\n")
                        self._kill_all(workers)
                        return 1
                time.sleep(poll_interval)
        except BaseException:
            # ctrl-C, store errors from the rescale poll, anything: the
            # gang must never be orphaned behind a dead supervisor
            self._kill_all(workers)
            raise

    def _check_rescale(self) -> bool:
        return False  # fixed-size pods never rescale

    def close(self):
        if self._store is not None:
            self._store.close()
            self._store = None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher "
                    "(reference: paddle.distributed.launch)")
    parser.add_argument("--nproc_per_node", type=int,
                        default=int(os.environ.get("PADDLE_NPROC", "1")))
    parser.add_argument("--master", default=None,
                        help="host:port of the rendezvous TCPStore "
                             "(default: serve one locally)")
    parser.add_argument("--job_id", default="default")
    parser.add_argument("--log_dir", default="log")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--heartbeat_timeout", type=float, default=None,
                        help="declare a rank hung when its heartbeat "
                             "(fleet.elastic.start_heartbeat) stalls "
                             "this many seconds; hung pods gang-restart")
    parser.add_argument("--module", action="store_true",
                        help="run script as a python module (-m)")
    parser.add_argument("--elastic", action="store_true",
                        help="supervise with the elastic manager: scale "
                             "events via the job store, exit-101 relaunch "
                             "protocol (fleet.elastic.ElasticJob)")
    parser.add_argument("--min_nproc", type=int, default=1,
                        help="elastic: lower world-size bound")
    parser.add_argument("--max_nproc", type=int, default=None,
                        help="elastic: upper world-size bound "
                             "(default: --nproc_per_node)")
    parser.add_argument("--scale", type=int, default=None, metavar="N",
                        help="operator mode: ask the running job at "
                             "--master/--job_id to rescale to N workers, "
                             "then exit (no script needed)")
    parser.add_argument("script", nargs="?")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.scale is not None:
        if not args.master:
            parser.error("--scale requires --master host:port")
        from ..fleet.elastic import request_scale
        request_scale(args.master, args.job_id, args.scale)
        return 0
    if not args.script:
        parser.error("script is required (unless using --scale)")

    if args.elastic:
        from ..fleet.elastic import ElasticJob
        job = ElasticJob(args.script, args.script_args,
                         args.nproc_per_node, min_nproc=args.min_nproc,
                         max_nproc=args.max_nproc,
                         master=args.master, log_dir=args.log_dir,
                         job_id=args.job_id,
                         max_restarts=args.max_restarts,
                         use_module=args.module,
                         heartbeat_timeout=args.heartbeat_timeout)
    else:
        job = LocalJob(args.script, args.script_args, args.nproc_per_node,
                       master=args.master, log_dir=args.log_dir,
                       job_id=args.job_id, max_restarts=args.max_restarts,
                       use_module=args.module,
                       heartbeat_timeout=args.heartbeat_timeout)
    try:
        return job.run()
    finally:
        job.close()
