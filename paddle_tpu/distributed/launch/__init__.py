"""Distributed launcher: ``python -m paddle_tpu.distributed.launch``.

Reference analog: python/paddle/distributed/launch/main.py:18 (the
``launch`` module: Pod/Container job model in
launch/controllers/collective.py, per-rank log files, a watchdog that
tears the pod down when any rank dies) plus the restart half of
fleet/elastic/manager.py:126 (gang restart with a bounded retry budget).

TPU-native shape: the unit of launch is one worker per HOST (all local
chips belong to one jax client; in-host parallelism comes from the mesh,
not processes), so this launcher manages host-level workers. Rendezvous
env rides the native TCPStore (csrc/tcp_store.cc) served from the
launcher process: workers get PADDLE_MASTER / MASTER_ADDR / MASTER_PORT /
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_RESTART_COUNT, the same
contract init_parallel_env consumes. Worker stdout/stderr stream to
``<log_dir>/workerlog.<rank>``. Failure policy is gang semantics, like
the reference pod watchdog: one dead rank kills the pod, and the pod
restarts as a unit up to ``--max_restarts`` times.

Elastic mode (``--elastic``) supervises the pod with
fleet.elastic.ElasticJob: world-size scale events watched on the job's
TCPStore (``--scale`` operator CLI / ``request_scale``), the exit-101
cooperative relaunch protocol, and bounds via ``--min_nproc`` /
``--max_nproc`` — the reference ElasticManager's contract with the
TCPStore standing in for etcd.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["LocalJob", "main", "classify_exit"]


def classify_exit(rc: Optional[int], escalated: bool = False) -> str:
    """Classify one worker's terminal state for the pod incident record:

    - ``clean``     — exit 0;
    - ``relaunch``  — exit 101, the cooperative elastic-relaunch code
      (``runtime.health.RELAUNCH_EXIT_CODE``): the worker detected a
      failure, saved, and asked to be respawned;
    - ``signal``    — killed by a signal (negative Popen returncode);
    - ``abandoned`` — never exited on its own: the launcher had to
      SIGKILL it (or it was still running when classified);
    - ``failed``    — any other nonzero exit.
    """
    if escalated or rc is None:
        return "abandoned"
    if rc == 0:
        return "clean"
    if rc == 101:
        return "relaunch"
    if rc < 0:
        return "signal"
    return "failed"


class _Worker:
    def __init__(self, rank: int, proc: subprocess.Popen, log_path: str):
        self.rank = rank
        self.proc = proc
        self.log_path = log_path


class LocalJob:
    """A pod of nproc workers on this host with gang restart."""

    # sentinel _watch returns when a scale event interrupts the gang
    # (only ElasticJob's _check_rescale can trigger it)
    RESCALE_RC = -1001

    def __init__(self, script: str, script_args: List[str], nproc: int,
                 master: Optional[str] = None, log_dir: str = "log",
                 job_id: str = "default", max_restarts: int = 3,
                 use_module: bool = False,
                 heartbeat_timeout: Optional[float] = None,
                 teardown_grace: float = 5.0):
        self.script = script
        self.script_args = script_args
        self.nproc = nproc
        self.log_dir = log_dir
        self.job_id = job_id
        self.max_restarts = max_restarts
        self.use_module = use_module
        self.heartbeat_timeout = heartbeat_timeout
        # failure teardown: how long surviving workers get to detect the
        # failure themselves, final-save, and flush their incident/trace
        # sidecars before the launcher starts signalling
        self.teardown_grace = float(teardown_grace)
        self.restart_count = 0
        self._store = None
        self._monitor = None
        # injectable for unit tests (no real sleeping/killing needed)
        self._sleep = time.sleep
        self._clock = time.monotonic
        if master:
            host, port = master.rsplit(":", 1)
            self.master_host, self.master_port = host, int(port)
        else:
            self.master_host, self.master_port = "127.0.0.1", 0

    def _start_store(self):
        from ..store import TCPStore
        self._store = TCPStore(self.master_host, self.master_port,
                               is_master=True, timeout=300)
        self.master_port = self._store.port
        if self.heartbeat_timeout:
            from ..fleet.elastic import HeartbeatMonitor
            self._monitor = HeartbeatMonitor(
                self._store, self.job_id, self.nproc,
                self.heartbeat_timeout)

    def _spawn_one(self, rank: int) -> _Worker:
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(self.nproc),
            "PADDLE_MASTER": f"{self.master_host}:{self.master_port}",
            "MASTER_ADDR": self.master_host,
            "MASTER_PORT": str(self.master_port),
            "PADDLE_JOB_ID": self.job_id,
            "PADDLE_RESTART_COUNT": str(self.restart_count),
        })
        # each rank's incidents_rank<N>.jsonl lands next to its workerlog
        # unless the operator pointed them somewhere explicitly; the
        # single-file override must NOT be inherited (every rank would
        # clobber the same path)
        env.pop("PADDLE_TPU_INCIDENTS_OUT", None)
        env.setdefault("PADDLE_TPU_INCIDENT_DIR", self.log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        log_path = os.path.join(self.log_dir, f"workerlog.{rank}")
        logf = open(log_path, "ab")
        cmd = [sys.executable]
        if self.use_module:
            cmd += ["-m", self.script]
        else:
            cmd += [self.script]
        cmd += self.script_args
        proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
        logf.close()
        return _Worker(rank, proc, log_path)

    def _kill_all(self, workers, grace: Optional[float] = None,
                  trigger: Optional[str] = None):
        """Tear the gang down, classifying every worker's exit.

        Escalation ladder: (1) an optional ``grace`` window in which
        workers may exit VOLUNTARILY — survivors of a peer failure use
        it to detect, final-save, and flush incident/trace sidecars
        before exiting 101; (2) SIGTERM + 5s; (3) SIGKILL (the worker is
        then classified ``abandoned``). Returns the per-worker exit
        record list; when ``trigger`` is given, also records a
        ``pod_teardown`` incident and persists the pod-level sidecar to
        ``<log_dir>/pod_incidents.jsonl``."""
        if grace:
            deadline = self._clock() + grace
            while (self._clock() < deadline
                   and any(w.proc.poll() is None for w in workers)):
                self._sleep(0.05)
        for w in workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        escalated = set()
        deadline = self._clock() + 5
        for w in workers:
            try:
                w.proc.wait(max(0.1, deadline - self._clock()))
            except subprocess.TimeoutExpired:
                escalated.add(w.rank)
                w.proc.kill()
                w.proc.wait()
        exits = [{"rank": w.rank, "pid": w.proc.pid,
                  "rc": w.proc.returncode,
                  "class": classify_exit(w.proc.returncode,
                                         escalated=w.rank in escalated)}
                 for w in workers]
        if trigger is not None:
            from ...runtime.watchdog import (record_incident,
                                             persist_incidents)
            record_incident("pod_teardown", trigger=trigger,
                            job_id=self.job_id,
                            restart=self.restart_count,
                            world_size=len(workers),
                            grace_s=grace or 0.0, workers=exits)
            pod_path = os.path.join(self.log_dir, "pod_incidents.jsonl")
            # the launcher's atexit flush must also target the pod file,
            # never a worker's incidents_rank<N>.jsonl (workers get a
            # cleaned env from _spawn_one, so this does not leak down)
            os.environ["PADDLE_TPU_INCIDENTS_OUT"] = pod_path
            try:
                persist_incidents(pod_path)
            except OSError as exc:
                sys.stderr.write(
                    f"launch: pod incident persist failed: {exc}\n")
        return exits

    def run(self, poll_interval: float = 0.2) -> int:
        """Run to completion with gang restart; returns the exit code."""
        if self._store is None:
            self._start_store()
        while True:
            workers = [self._spawn_one(r) for r in range(self.nproc)]
            rc = self._watch(workers, poll_interval)
            if rc == 0:
                return 0
            if self.restart_count >= self.max_restarts:
                sys.stderr.write(
                    f"launch: pod failed rc={rc} after "
                    f"{self.restart_count} restarts (budget "
                    f"{self.max_restarts}); giving up\n")
                return rc
            self.restart_count += 1
            sys.stderr.write(
                f"launch: worker failure rc={rc}; gang restart "
                f"{self.restart_count}/{self.max_restarts}\n")

    def _watch(self, workers, poll_interval) -> int:
        """Block until all workers exit 0 (return 0) or any fails
        (kill the gang, return its rc)."""
        if self._monitor is not None:
            self._monitor.reset()
        try:
            while True:
                alive = False
                for w in workers:
                    rc = w.proc.poll()
                    if rc is None:
                        alive = True
                    elif rc != 0:
                        sys.stderr.write(
                            f"launch: rank {w.rank} exited rc={rc} "
                            f"(log: {w.log_path})\n")
                        exits = self._kill_all(
                            workers, grace=self.teardown_grace,
                            trigger=f"rank {w.rank} exited rc={rc}")
                        return self._pod_rc(rc, exits)
                if not alive:
                    return 0
                if self._check_rescale():
                    self._kill_all(workers)
                    return self.RESCALE_RC
                if self._monitor is not None:
                    stale = self._monitor.stale_ranks(self.restart_count)
                    stale = [r for r in stale
                             if workers[r].proc.poll() is None]
                    if stale:
                        sys.stderr.write(
                            f"launch: ranks {stale} heartbeat-stale "
                            f"(> {self.heartbeat_timeout}s): "
                            "declaring hung\n")
                        exits = self._kill_all(
                            workers, grace=self.teardown_grace,
                            trigger=f"ranks {stale} heartbeat-stale")
                        return self._pod_rc(1, exits)
                time.sleep(poll_interval)
        except BaseException:
            # ctrl-C, store errors from the rescale poll, anything: the
            # gang must never be orphaned behind a dead supervisor
            self._kill_all(workers)
            raise

    @staticmethod
    def _pod_rc(rc: int, exits) -> int:
        """Pod exit code after a failure teardown. If ANY worker exited
        with the cooperative relaunch code during the grace window (a
        survivor that detected the failure, saved, and asked for a
        respawn), the pod's verdict is 101 — the elastic supervisor then
        relaunches without burning restart budget even when the
        first-detected rc was a raw crash code."""
        if any(e["class"] == "relaunch" for e in exits):
            return 101
        return rc

    def _check_rescale(self) -> bool:
        return False  # fixed-size pods never rescale

    def close(self):
        if self._store is not None:
            self._store.close()
            self._store = None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher "
                    "(reference: paddle.distributed.launch)")
    parser.add_argument("--nproc_per_node", type=int,
                        default=int(os.environ.get("PADDLE_NPROC", "1")))
    parser.add_argument("--master", default=None,
                        help="host:port of the rendezvous TCPStore "
                             "(default: serve one locally)")
    parser.add_argument("--job_id", default="default")
    parser.add_argument("--log_dir", default="log")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--heartbeat_timeout", type=float, default=None,
                        help="declare a rank hung when its heartbeat "
                             "(fleet.elastic.start_heartbeat) stalls "
                             "this many seconds; hung pods gang-restart")
    parser.add_argument("--teardown_grace", type=float, default=5.0,
                        help="failure teardown: seconds surviving "
                             "workers get to exit voluntarily (final "
                             "save + incident/trace sidecar flush) "
                             "before SIGTERM/SIGKILL escalation")
    parser.add_argument("--module", action="store_true",
                        help="run script as a python module (-m)")
    parser.add_argument("--elastic", action="store_true",
                        help="supervise with the elastic manager: scale "
                             "events via the job store, exit-101 relaunch "
                             "protocol (fleet.elastic.ElasticJob)")
    parser.add_argument("--min_nproc", type=int, default=1,
                        help="elastic: lower world-size bound")
    parser.add_argument("--max_nproc", type=int, default=None,
                        help="elastic: upper world-size bound "
                             "(default: --nproc_per_node)")
    parser.add_argument("--scale", type=int, default=None, metavar="N",
                        help="operator mode: ask the running job at "
                             "--master/--job_id to rescale to N workers, "
                             "then exit (no script needed)")
    parser.add_argument("script", nargs="?")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.scale is not None:
        if not args.master:
            parser.error("--scale requires --master host:port")
        from ..fleet.elastic import request_scale
        request_scale(args.master, args.job_id, args.scale)
        return 0
    if not args.script:
        parser.error("script is required (unless using --scale)")

    if args.elastic:
        from ..fleet.elastic import ElasticJob
        job = ElasticJob(args.script, args.script_args,
                         args.nproc_per_node, min_nproc=args.min_nproc,
                         max_nproc=args.max_nproc,
                         master=args.master, log_dir=args.log_dir,
                         job_id=args.job_id,
                         max_restarts=args.max_restarts,
                         use_module=args.module,
                         heartbeat_timeout=args.heartbeat_timeout,
                         teardown_grace=args.teardown_grace)
    else:
        job = LocalJob(args.script, args.script_args, args.nproc_per_node,
                       master=args.master, log_dir=args.log_dir,
                       job_id=args.job_id, max_restarts=args.max_restarts,
                       use_module=args.module,
                       heartbeat_timeout=args.heartbeat_timeout,
                       teardown_grace=args.teardown_grace)
    try:
        return job.run()
    finally:
        job.close()
