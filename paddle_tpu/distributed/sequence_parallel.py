"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference snapshot has NO sequence parallelism (SURVEY.md §5: zero hits
for ring_attention/context_parallel/ulysses) — this is a to-design feature
the TPU build adds natively on top of mesh collectives:

- **Ring attention** (context parallel): Q/K/V sharded on the sequence dim
  over a mesh axis; K/V blocks rotate around the ring via lax.ppermute
  (ICI neighbor DMA) while each device accumulates its Q-block's attention
  with an online-softmax merge — memory O(S/n), exact causal attention.
- **Ulysses**: all_to_all reshards [B, S/n, H, D] -> [B, S, H/n, D], runs
  full attention locally on a head slice, and reshards back — one
  all_to_all each way over the axis, best when H % n == 0.

Both are exposed two ways: axis-level functions usable inside an existing
shard_map (the building-block form), and mesh-level wrappers that apply
shard_map themselves.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention", "ring_attention_sharded",
           "ulysses_attention_sharded"]


def _block_attn(q, k, v, scale, q_off, k_off, causal):
    """Blockwise attention stats for online-softmax merging.

    q: [B, Sq, H, D], k/v: [B, Sk, H, D]; returns (m, l, acc) with
    m,l: [B, H, Sq] f32 and acc: [B, H, Sq, D] f32 (un-normalized).
    q_off/k_off: global offsets of the blocks for causal masking.
    """
    if k.shape[2] != q.shape[2]:
        # GQA: expand kv heads at USE time only — the ring rotates the
        # small nkv blocks, not nh/nkv redundant copies
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # [B,H,Sq,D]
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
    m = jnp.max(s, axis=-1)                          # [B,H,Sq]
    # fully-masked rows: keep m finite so exp() is well-defined
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return m_safe, l, acc


def _merge(m1, l1, a1, m2, l2, a2):
    """Merge two online-softmax partial results."""
    m = jnp.maximum(m1, m2)
    c1 = jnp.where(l1 > 0, jnp.exp(m1 - m), 0.0)
    c2 = jnp.where(l2 > 0, jnp.exp(m2 - m), 0.0)
    return m, l1 * c1 + l2 * c2, a1 * c1[..., None] + a2 * c2[..., None]


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Exact (causal) attention with sequence sharded over `axis_name`.

    Call INSIDE shard_map: q/k/v are the local [B, S_local, H, D] blocks.
    K/V rotate around the ring; n-1 ppermute steps overlap with compute.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_off = idx * Sl

    def step(i, carry):
        m, l, acc, kc, vc = carry
        # kv block currently held arrived from device (idx - i) mod n
        src = (idx - i) % n
        k_off = src * Sl
        bm, bl, bacc = _block_attn(q, kc, vc, scale, q_off, k_off, causal)
        m, l, acc = _merge(m, l, acc, bm, bl, bacc)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    m0 = jnp.full((B, H, Sl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Sl), jnp.float32)
    a0 = jnp.zeros((B, H, Sl, D), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, n, step, (m0, l0, a0, k, v))
    out = acc / jnp.maximum(l, 1e-38)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)   # [B,Sl,H,D]


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      attn_fn=None):
    """Ulysses SP: head<->sequence all_to_all around a local full-sequence
    attention. Call INSIDE shard_map with seq-sharded [B, S/n, H, D]."""
    n = lax.axis_size(axis_name)
    H = q.shape[2]
    assert H % n == 0, f"heads {H} not divisible by sp degree {n}"

    def to_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):    # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if attn_fn is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
        m, l, acc = _block_attn(qh, kh, vh, scale, 0, 0, causal)
        out = acc / jnp.maximum(l, 1e-38)[..., None]
        out = jnp.swapaxes(out, 1, 2).astype(q.dtype)
    else:
        out = attn_fn(qh, kh, vh)
    return to_seq(out)


def _sharded(fn, mesh, axis_name):
    # manualize ONLY the sequence axis: on a hybrid mesh the batch dim
    # stays dp-sharded and the head dim mp-sharded in the auto (GSPMD)
    # sense — full-mesh manualization would all-gather both and run the
    # attention redundantly on every dp/mp slice
    spec = P(None, axis_name, None, None)
    return jax.shard_map(fn, mesh=mesh,
                         in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis_name},
                         check_vma=False)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "sp",
                           causal: bool = True):
    """Mesh-level wrapper: q/k/v are global [B, S, H, D]; S is (re)sharded
    over `axis_name` and ring attention runs under shard_map."""
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal)
    return _sharded(lambda a, b, c: fn(a, b, c), mesh, axis_name)(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, axis_name: str = "sp",
                              causal: bool = True):
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal)
    return _sharded(lambda a, b, c: fn(a, b, c), mesh, axis_name)(q, k, v)
