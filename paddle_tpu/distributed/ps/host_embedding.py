"""Host-RAM sharded embedding service — the parameter-server replacement.

Reference analog: paddle/fluid/distributed/ps/table/memory_sparse_table.cc
(sharded host-memory embedding rows with row-wise optimizer state, pull/
push RPC plane via brpc_ps_server.cc) and the heter-PS pull_sparse/
push_sparse dense-tower pattern (framework/fleet/heter_ps/).

TPU-native design: the table never enters HBM. Rows live in host RAM,
row-sharded `id % n_shards` across shard holders that are either

- **local** (default): numpy arrays in this process — the one-host case,
  covering embeddings up to host-RAM size on a single machine; or
- **rpc**: `EmbeddingShard`s hosted by `paddle_tpu.distributed.rpc`
  workers (the brpc PsService analog) — host-RAM scale-out across the
  pod's CPU side over DCN.

Device integration is a `jax.custom_vjp` around `io_callback`: the
forward looks up only the B x D rows the batch touches (pull_sparse),
the backward sparse-pushes row gradients into the shard's row-wise
optimizer (push_sparse; SGD or Adagrad, duplicate ids accumulated with
np.add.at). Ordered callbacks keep step k's push before step k+1's pull.
Updates are applied as the gradients arrive — the same asynchronous-SGD
contract the reference PS trains recommenders with.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["EmbeddingShard", "HostEmbedding"]


class EmbeddingShard:
    """One host-RAM shard: global id g lives on shard g % n_shards at
    local row g // n_shards (memory_sparse_table's shard_num layout)."""

    def __init__(self, n_rows: int, dim: int, optimizer: str = "sgd",
                 lr: float = 0.1, seed: int = 0, scale: float = 0.01,
                 dtype=np.float32):
        rng = np.random.default_rng(seed)
        self.table = (rng.standard_normal((n_rows, dim)) * scale).astype(
            dtype)
        self.optimizer = optimizer
        self.lr = float(lr)
        if optimizer == "adagrad":
            self._accum = np.zeros((n_rows, 1), np.float32)
        elif optimizer != "sgd":
            raise ValueError(
                f"unknown row optimizer {optimizer!r}; expected 'sgd' or "
                "'adagrad'")

    @property
    def nbytes(self) -> int:
        n = self.table.nbytes
        if self.optimizer == "adagrad":
            n += self._accum.nbytes
        return n

    def lookup(self, rows: np.ndarray) -> np.ndarray:
        return self.table[rows]

    def push(self, rows: np.ndarray, grads: np.ndarray) -> None:
        """Row-wise sparse update; duplicate ids accumulate first so one
        batch touching a row twice applies one combined step."""
        uniq, inv = np.unique(rows, return_inverse=True)
        acc = np.zeros((uniq.shape[0], grads.shape[1]), np.float32)
        np.add.at(acc, inv, grads.astype(np.float32))
        if self.optimizer == "adagrad":
            self._accum[uniq] += np.sum(acc * acc, axis=1, keepdims=True) \
                / acc.shape[1]
            step = acc / (np.sqrt(self._accum[uniq]) + 1e-8)
        else:
            step = acc
        self.table[uniq] -= (self.lr * step).astype(self.table.dtype)

    def state_dict(self):
        sd = {"table": self.table, "optimizer": self.optimizer,
              "lr": self.lr}
        if self.optimizer == "adagrad":
            sd["accum"] = self._accum
        return sd

    def load_state_dict(self, sd):
        if sd.get("optimizer", self.optimizer) != self.optimizer:
            raise ValueError(
                f"checkpoint row optimizer {sd['optimizer']!r} does not "
                f"match this shard's {self.optimizer!r}; construct the "
                "shard with the checkpoint's optimizer to keep its "
                "accumulator state meaningful")
        self.table[...] = sd["table"]
        if self.optimizer == "adagrad":
            self._accum[...] = sd["accum"]


# registry used by rpc shard holders: the rpc plane ships (fn, args), so
# shard methods are addressed by key through these module-level functions
_SHARDS: dict = {}


def create_shard(key: str, n_rows: int, dim: int, **kw) -> int:
    _SHARDS[key] = EmbeddingShard(n_rows, dim, **kw)
    return n_rows


def shard_lookup(key: str, rows: np.ndarray) -> np.ndarray:
    return _SHARDS[key].lookup(rows)


def shard_push(key: str, rows: np.ndarray, grads: np.ndarray) -> None:
    _SHARDS[key].push(rows, grads)


def shard_nbytes(key: str) -> int:
    return _SHARDS[key].nbytes


def shard_state_dict(key: str):
    return _SHARDS[key].state_dict()


def shard_load_state_dict(key: str, sd) -> None:
    _SHARDS[key].load_state_dict(sd)


class HostEmbedding:
    """Sharded host-RAM embedding with device-side lookup/push.

    Use inside jitted steps or eager autograd: ``emb(ids)`` returns the
    looked-up rows and its backward pushes sparse row gradients into the
    host optimizer. ``device_budget_bytes`` documents the intent: the
    table may exceed accelerator memory arbitrarily — only the touched
    rows ever transfer.

    rpc mode: pass ``rpc_workers=[name, ...]`` after
    ``distributed.rpc.init_rpc`` — shard i lives on worker i % len,
    created remotely; lookups/pushes ride ``rpc_sync``.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 n_shards: int = 1, optimizer: str = "sgd", lr: float = 0.1,
                 seed: int = 0, dtype=np.float32,
                 rpc_workers: Optional[List[str]] = None,
                 device_budget_bytes: Optional[int] = None,
                 name: str = "host_embedding"):
        import jax

        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.n_shards = int(n_shards)
        self.dtype = np.dtype(dtype)
        self.name = name
        self._optimizer = optimizer
        self._lr = float(lr)
        self._seed = int(seed)
        self._rpc_workers = list(rpc_workers) if rpc_workers else None
        self._rows_per = [len(range(s, self.num_embeddings, self.n_shards))
                          for s in range(self.n_shards)]
        rows_per = self._rows_per
        self._local: List[Optional[EmbeddingShard]] = []
        if self._rpc_workers is None:
            for s in range(self.n_shards):
                self._local.append(EmbeddingShard(
                    rows_per[s], embedding_dim, optimizer=optimizer, lr=lr,
                    seed=seed + s, dtype=self.dtype))
        else:
            for s in range(self.n_shards):
                self._create_remote_shard(s)
        if device_budget_bytes is not None \
                and self.table_nbytes <= device_budget_bytes:
            import warnings
            warnings.warn(
                f"HostEmbedding {name!r}: table ({self.table_nbytes} B) "
                f"fits the device budget ({device_budget_bytes} B); a "
                "mesh-sharded dense embedding (models vocab-parallel "
                "embedding) would be faster", stacklevel=2)
        self._fn = self._build_fn()

    # -- shard plane --------------------------------------------------------
    _RPC_FNS = {"lookup": shard_lookup, "push": shard_push,
                "nbytes": shard_nbytes, "state_dict": shard_state_dict,
                "load_state_dict": shard_load_state_dict}

    def _create_remote_shard(self, s: int) -> None:
        from .. import rpc
        w = self._rpc_workers[s % len(self._rpc_workers)]
        rpc.rpc_sync(w, create_shard, args=(
            f"{self.name}/shard{s}", self._rows_per[s],
            self.embedding_dim),
            kwargs=dict(optimizer=self._optimizer, lr=self._lr,
                        seed=self._seed + s, dtype=self.dtype))

    def _shard_call(self, s: int, method: str, *args):
        if self._rpc_workers is None:
            attr = getattr(self._local[s], method)
            return attr(*args) if callable(attr) else attr  # nbytes: prop
        from .. import rpc
        w = self._rpc_workers[s % len(self._rpc_workers)]
        return rpc.rpc_sync(w, self._RPC_FNS[method],
                            args=(f"{self.name}/shard{s}", *args))

    @property
    def table_nbytes(self) -> int:
        return sum(self._shard_call(s, "nbytes")
                   for s in range(self.n_shards))

    def _check_ids(self, ids: np.ndarray) -> None:
        # numpy's wraparound indexing would silently serve (and on push,
        # corrupt) an unrelated row for a bad id; error like the dense
        # embedding's bounds contract instead
        bad = (ids < 0) | (ids >= self.num_embeddings)
        if bad.any():
            raise IndexError(
                f"HostEmbedding {self.name!r}: ids out of range "
                f"[0, {self.num_embeddings}): "
                f"{np.unique(ids[bad])[:10].tolist()}")

    def _host_lookup(self, flat_ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(flat_ids, np.int64)
        self._check_ids(ids)
        out = np.empty((ids.shape[0], self.embedding_dim), self.dtype)
        sid = ids % self.n_shards
        for s in range(self.n_shards):
            mask = sid == s
            if not mask.any():
                continue
            out[mask] = self._shard_call(s, "lookup",
                                         ids[mask] // self.n_shards)
        return out

    def _host_push(self, flat_ids: np.ndarray, grads: np.ndarray) -> None:
        ids = np.asarray(flat_ids, np.int64)
        self._check_ids(ids)
        g = np.asarray(grads)
        sid = ids % self.n_shards
        for s in range(self.n_shards):
            mask = sid == s
            if not mask.any():
                continue
            self._shard_call(s, "push", ids[mask] // self.n_shards,
                             g[mask])

    # -- explicit pull/push (the reference's pull_sparse/push_sparse) -------
    def pull_sparse(self, ids) -> np.ndarray:
        ids = np.asarray(ids)
        out = self._host_lookup(ids.reshape(-1))
        return out.reshape(tuple(ids.shape) + (self.embedding_dim,))

    def push_sparse(self, ids, grads) -> None:
        ids = np.asarray(ids)
        self._host_push(ids.reshape(-1),
                        np.asarray(grads).reshape(-1, self.embedding_dim))

    # -- device plane -------------------------------------------------------
    def _build_fn(self):
        import jax
        import jax.numpy as jnp
        from jax.experimental import io_callback

        dim = self.embedding_dim
        jdtype = jnp.dtype(self.dtype)

        # The lookup is custom_vjp'd over (ids, token). ids are integers
        # (no cotangent); `token` is a differentiable scalar the caller
        # threads through their param tree — autodiff only invokes a
        # custom_vjp whose inputs are on the differentiation path, so the
        # token is what makes the backward (the sparse push) fire inside
        # grad-of-loss-wrt-params. Its own gradient is zero.
        @jax.custom_vjp
        def lookup(ids, token):
            flat = ids.reshape(-1)
            out = io_callback(
                self._host_lookup,
                jax.ShapeDtypeStruct((flat.shape[0], dim), jdtype),
                flat, ordered=True)
            del token  # participates in autodiff, not in the value
            return out.reshape(tuple(ids.shape) + (dim,))

        def fwd(ids, token):
            return lookup(ids, token), (ids, token)

        def bwd(res, g):
            ids, token = res
            flat = ids.reshape(-1)
            gf = g.reshape((-1, dim))
            io_callback(self._host_push, None, flat, gf, ordered=True)
            return (np.zeros(ids.shape, jax.dtypes.float0),
                    jnp.zeros_like(token))

        lookup.defvjp(fwd, bwd)
        return lookup

    def init_token(self):
        """Differentiable scalar to place in the training-step param tree
        and pass to ``__call__`` — see _build_fn. Gradient is always 0,
        so any optimizer leaves it at 1."""
        import jax.numpy as jnp
        return jnp.ones((), jnp.float32)

    def __call__(self, ids, token=None):
        from ...core.tensor import Tensor, apply_op
        if isinstance(ids, Tensor):
            if token is None:
                if not hasattr(self, "_eager_token"):
                    self._eager_token = Tensor(self.init_token(),
                                               stop_gradient=False)
                token = self._eager_token
            # token requires grad -> the tape records this op and eager
            # backward() reaches the vjp whose side effect is the push
            return apply_op(self._fn, ids, token,
                            op_name="host_embedding_lookup")
        if token is None:
            raise ValueError(
                "HostEmbedding under jit needs the token: include "
                "emb.init_token() in the params you differentiate and "
                "pass it as emb(ids, token) — without it autodiff never "
                "invokes the backward that pushes the row gradients")
        return self._fn(ids, token)

    def _shard_call_all(self, method: str, args_of=None):
        """Fan the same method out to every shard; rpc mode issues all
        calls concurrently (rpc_async) — a sequential gather would
        serialize n_shards full-table DCN transfers."""
        args_of = args_of or (lambda s: ())
        if self._rpc_workers is None:
            return [self._shard_call(s, method, *args_of(s))
                    for s in range(self.n_shards)]
        from .. import rpc
        futs = []
        for s in range(self.n_shards):
            w = self._rpc_workers[s % len(self._rpc_workers)]
            futs.append(rpc.rpc_async(
                w, self._RPC_FNS[method],
                args=(f"{self.name}/shard{s}", *args_of(s))))
        return [f.result() for f in futs]

    # -- checkpoint ---------------------------------------------------------
    # reference: memory_sparse_table.cc Save/Load — the PS persists its
    # tables and a restarted shard holder reloads its slice. rpc mode
    # gathers/scatters each shard's state over the rpc plane.
    def state_dict(self):
        states = self._shard_call_all("state_dict")
        return {f"shard{s}": states[s] for s in range(self.n_shards)}

    def load_state_dict(self, sd):
        self._shard_call_all("load_state_dict",
                             lambda s: (sd[f"shard{s}"],))

    def _shard_file(self, dirname: str, s: int) -> str:
        import os
        safe = self.name.replace("/", "_")
        return os.path.join(dirname, f"{safe}.shard{s}.npz")

    def save(self, dirname: str) -> None:
        """Persist every shard to ``dirname`` (one .npz per shard), from
        whichever holder owns it. Written atomically (tmp + rename) so a
        crash mid-save never leaves a torn shard file."""
        import os
        os.makedirs(dirname, exist_ok=True)
        states = self._shard_call_all("state_dict")
        for s in range(self.n_shards):
            sd = states[s]
            path = self._shard_file(dirname, s)
            tmp = path + ".tmp"
            arrays = {"table": sd["table"],
                      "optimizer": np.asarray(sd["optimizer"]),
                      "lr": np.asarray(sd["lr"], np.float64)}
            if "accum" in sd:
                arrays["accum"] = sd["accum"]
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, path)

    def _load_shard_sd(self, dirname: str, s: int):
        import os
        path = self._shard_file(dirname, s)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path}: no checkpoint for shard {s} of "
                f"{self.name!r}; was save() called with this dirname?")
        with np.load(path) as z:
            sd = {"table": z["table"], "optimizer": str(z["optimizer"]),
                  "lr": float(z["lr"])}
            if "accum" in z:
                sd["accum"] = z["accum"]
        return sd

    def load(self, dirname: str) -> None:
        """Reload every shard from a save() directory."""
        sds = [self._load_shard_sd(dirname, s)
               for s in range(self.n_shards)]
        self._shard_call_all("load_state_dict", lambda s: (sds[s],))

    def restore_shard(self, s: int, dirname: str) -> None:
        """Recover ONE shard after its holder crashed and rejoined: re-
        create the shard on the (restarted) worker that owns slot ``s``
        and reload its slice from the save() directory. rpc endpoints
        must be refreshed first (``rpc.refresh_worker_infos()``) so the
        worker name resolves to the new process.

        The recovery contract is the reference PS's: state since the
        last save() is lost for this shard (async-SGD tolerates it);
        every other shard is untouched.
        """
        if self._rpc_workers is None:
            # local shards share the process's lifetime; reconstruct in
            # place for API symmetry
            self._local[s] = EmbeddingShard(
                self._rows_per[s], self.embedding_dim,
                optimizer=self._optimizer, lr=self._lr,
                seed=self._seed + s, dtype=self.dtype)
        else:
            self._create_remote_shard(s)
        self._shard_call(s, "load_state_dict",
                         self._load_shard_sd(dirname, s))
