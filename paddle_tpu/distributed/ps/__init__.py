"""Parameter-server-class training: host-RAM sharded embeddings.

Reference: paddle/fluid/distributed/ps/ (~40k LoC: brpc-based PsService,
sharded sparse tables `table/memory_sparse_table.cc`, dense/sparse
pull/push, geo-async SGD) surfaced as fleet's ParameterServerOptimizer
and the CPU "heter" trainers. Its purpose: train embedding tables that
exceed accelerator memory, with sparse row-wise updates.

TPU-native mapping — two regimes:

- **Fits the pod**: shard the dense embedding over the mesh
  ('mp'/'dp' axes, e.g. models.llama vocab-parallel embedding); lookups
  are XLA collectives over ICI, optimizer state shards with ZeRO
  (distributed/sharding). This is the default and the fast path.
- **Exceeds accelerator memory** (recommender-scale sparse tables):
  `HostEmbedding` here — delivered at `ps/host_embedding.py` — keeps
  row-sharded tables in host RAM (locally, or on
  `paddle_tpu.distributed.rpc` workers = the brpc PsService analog),
  pulls only the touched rows to the device per step, and sparse-pushes
  row gradients into a host-side row-wise optimizer (SGD/Adagrad),
  matching the reference's asynchronous pull_sparse/push_sparse
  contract (`memory_sparse_table.cc`).

The async/geo-async *dense* PS modes stay out of scope: synchronous
SPMD over the mesh replaces them by construction — asynchronous dense
updates would fork the execution model for a hardware profile (loose
CPU clusters) that TPU deployments do not have.

`ParameterServerOptimizer` (the fleet strategy face) still raises,
pointing at the two supported regimes, so configs that request the
reference's CPU-cluster PS topology fail loudly with the migration path.
"""
from __future__ import annotations

from .host_embedding import EmbeddingShard, HostEmbedding

__all__ = ["ParameterServerOptimizer", "is_supported", "HostEmbedding",
           "EmbeddingShard"]

_MSG = ("the reference's CPU-cluster parameter-server topology is not "
        "replicated on the TPU stack: shard dense embeddings over the "
        "mesh, or use distributed.ps.HostEmbedding for tables that "
        "exceed accelerator memory (see paddle_tpu.distributed.ps "
        "docstring)")


def is_supported() -> bool:
    return False


class ParameterServerOptimizer:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)
