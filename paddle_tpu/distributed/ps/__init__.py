"""Parameter-server training: explicit scope-out (SURVEY §2.5 #10).

Reference: paddle/fluid/distributed/ps/ (~40k LoC: brpc-based
PsService, DownpourBrpcPs tables, dense/sparse table shards, geo-async
SGD) surfaced as fleet's ParameterServerOptimizer
(python/paddle/distributed/fleet/meta_optimizers/ps_optimizer.py) and
the CPU "heter" trainers.

Decision: OUT OF SCOPE for the TPU framework, by design rather than
omission.

Why:
- The PS stack exists to scale sparse embedding tables beyond
  accelerator memory on CPU clusters with asynchronous updates. On TPU
  pods the same workload maps onto synchronous SPMD: embedding tables
  shard over the mesh ('mp'/'dp' axes, e.g. models.llama vocab-parallel
  embedding), lookups are XLA all-to-all/gather collectives over ICI,
  and optimizer state shards with ZeRO (distributed/sharding). The
  100B-feature / trillion-parameter claims the reference makes for PS
  (README "Ultra-Large-Scale Training") are reached on TPU by adding
  hosts to the mesh, not by a side channel of CPU parameter servers.
- Asynchronous/geo-async SGD semantics conflict with the deterministic
  synchronous step this framework compiles (one jit'd update over a
  mesh); supporting them would fork the execution model for a hardware
  profile (loose CPU clusters + RPC) that TPU deployments do not have.
- The remaining PS use case — streaming recommender models with
  out-of-accelerator-memory embeddings — needs a DCN-sharded embedding
  service. That is deliverable as a separate service in front of this
  framework (host-RAM embedding shards + device dense towers), and the
  extension points it needs already exist: distributed.rpc for the
  fetch/push plane and utils.cpp_extension's XLA FFI host ops for the
  lookup kernels.

The symbols below raise with this explanation so fleet configs that
request PS fail loudly with the migration path instead of silently
training without it.
"""
from __future__ import annotations

__all__ = ["ParameterServerOptimizer", "is_supported"]

_MSG = ("parameter-server training is out of scope on the TPU stack: "
        "shard embeddings over the mesh instead (see "
        "paddle_tpu.distributed.ps docstring for the rationale and "
        "migration path)")


def is_supported() -> bool:
    return False


class ParameterServerOptimizer:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)
