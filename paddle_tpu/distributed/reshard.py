"""Topology-elastic checkpoint restore.

Reference analog: auto_parallel's dist_saver merge/re-slice pass
(python/paddle/distributed/auto_parallel/dist_saver.py — per-rank shard
files re-merged by dist_attr and re-sliced for the load topology) and
fleet's sharded save_persistables.

TPU-native: every committed checkpoint carries a topology/sharding block
in its crash-consistency manifest (``fault_tolerance.write_manifest
extra=``): mesh axis degrees, world size, per-param PartitionSpecs,
per-rank RNG streams, and the data-pipeline cursor. Restoring onto a
*different* ``(dp, mp, pp)`` mesh — the routine outcome of a preemptible
TPU-pod relaunch — then needs no resharding service: the full logical
arrays are materialized host-side (numpy), each device's slice is cut by
the saved spec re-bound to the *current* mesh, and
``jax.make_array_from_callback`` places shard-by-shard so no device ever
sees more than its own piece.

The slicing/gathering math is pure numpy (:func:`slice_for_shard`,
:func:`reslice`, :func:`gather_full`) so it is unit-testable without
devices and reusable by hosts reassembling per-rank shard files.

Typical elastic resume, new world size included::

    mgr = ft.CheckpointManager(root).attach_data(loader)
    state, step = reshard.restore_resharded(root, data=loader, rng=True)
    # state now lives on THIS run's mesh, loader resumes sample-exact
"""
from __future__ import annotations

import itertools
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "spec_to_json", "spec_from_json", "shard_counts", "shard_shape",
    "slice_for_shard", "mesh_coords_iter", "reslice", "gather_full",
    "topology_block", "sharding_specs", "rng_bundle", "apply_rng_bundle",
    "manifest_extra", "apply_manifest_state", "place", "place_tree",
    "restore_resharded", "host_full",
]


# ---------------------------------------------------------------------------
# sharding-spec serialization
# ---------------------------------------------------------------------------

def _axes_of(entry) -> List[str]:
    if entry is None:
        return []
    if isinstance(entry, (list, tuple)):
        return [str(a) for a in entry]
    return [str(entry)]


def spec_to_json(spec) -> List[Optional[List[str]]]:
    """PartitionSpec (or any per-dim sequence of axis names) -> JSON:
    one entry per array dim, ``None`` (replicated) or the list of mesh
    axes that dim shards over."""
    out: List[Optional[List[str]]] = []
    for entry in tuple(spec):
        axes = _axes_of(entry)
        out.append(axes if axes else None)
    return out


def spec_from_json(obj):
    """Inverse of :func:`spec_to_json` (requires jax)."""
    from jax.sharding import PartitionSpec
    entries = []
    for e in obj or []:
        if not e:
            entries.append(None)
        elif len(e) == 1:
            entries.append(e[0])
        else:
            entries.append(tuple(e))
    return PartitionSpec(*entries)


# ---------------------------------------------------------------------------
# host-side slicing math (pure numpy — no devices involved)
# ---------------------------------------------------------------------------

def _pad_spec(spec_json, ndim: int) -> List[Optional[List[str]]]:
    s = list(spec_json or [])
    if len(s) > ndim:
        raise ValueError(
            f"sharding spec {spec_json!r} has more entries than array "
            f"dims ({ndim})")
    return s + [None] * (ndim - len(s))


def shard_counts(spec_json, dims: Dict[str, int], ndim: int) -> List[int]:
    """Number of shards along each array dim: the product of the mesh
    degrees of the axes that dim shards over (1 for replicated dims and
    axes the mesh does not carry)."""
    counts = []
    for axes in _pad_spec(spec_json, ndim):
        n = 1
        for a in (axes or []):
            n *= int(dims.get(a, 1))
        counts.append(n)
    return counts


def slice_for_shard(shape, spec_json, dims: Dict[str, int],
                    coords: Dict[str, int]) -> Tuple[slice, ...]:
    """The index slice of the full array owned by the device at mesh
    coordinates ``coords`` (axis name -> coordinate). Multi-axis dims
    compose row-major over the axis tuple — GSPMD's layout convention,
    cross-checked against NamedSharding.devices_indices_map in tests."""
    out = []
    for size, axes in zip(tuple(shape), _pad_spec(spec_json, len(shape))):
        n = 1
        for a in (axes or []):
            n *= int(dims.get(a, 1))
        if n > 1 and size % n:
            raise ValueError(
                f"dim of size {size} does not divide into {n} shards "
                f"(axes {axes!r} over mesh {dims!r}); elastic restore "
                f"needs evenly sharded dims")
        i = 0
        for a in (axes or []):
            i = i * int(dims.get(a, 1)) + int(coords.get(a, 0))
        step = size // n
        out.append(slice(i * step, (i + 1) * step))
    return tuple(out)


def shard_shape(shape, spec_json, dims: Dict[str, int]) -> Tuple[int, ...]:
    """Per-shard shape under ``spec_json`` on a mesh of ``dims``."""
    sls = slice_for_shard(shape, spec_json, dims, {})
    return tuple(sl.stop - sl.start for sl in sls)


def mesh_coords_iter(dims: Dict[str, int]):
    """Every mesh coordinate dict of a mesh with the given axis degrees."""
    axes = list(dims)
    for combo in itertools.product(*[range(int(dims[a])) for a in axes]):
        yield dict(zip(axes, combo))


def _coords_key(coords: Dict[str, int]) -> Tuple[Tuple[str, int], ...]:
    return tuple(sorted(coords.items()))


def reslice(full, spec_json, dims: Dict[str, int]
            ) -> Dict[Tuple[Tuple[str, int], ...], np.ndarray]:
    """Cut a full host array into its per-device shards: coords-key ->
    ndarray. Replicated dims produce identical copies, exactly like the
    device placement would."""
    full = np.asarray(full)
    return {
        _coords_key(c): full[slice_for_shard(full.shape, spec_json, dims, c)]
        for c in mesh_coords_iter(dims)
    }


def gather_full(shards: Dict[Tuple[Tuple[str, int], ...], np.ndarray],
                shape, spec_json, dims: Dict[str, int],
                dtype=None) -> np.ndarray:
    """Reassemble the full logical array from per-device shards (inverse
    of :func:`reslice`; replicated copies overwrite idempotently)."""
    if dtype is None:
        dtype = next(iter(shards.values())).dtype
    out = np.empty(tuple(shape), dtype=dtype)
    for key, piece in shards.items():
        coords = dict(key)
        sl = slice_for_shard(shape, spec_json, dims, coords)
        expect = tuple(s.stop - s.start for s in sl)
        if tuple(piece.shape) != expect:
            raise ValueError(
                f"shard at {coords!r} has shape {tuple(piece.shape)}, "
                f"spec {spec_json!r} over mesh {dims!r} expects {expect}")
        out[sl] = piece
    return out


# ---------------------------------------------------------------------------
# manifest block: topology + per-param specs + RNG + data cursor
# ---------------------------------------------------------------------------

def topology_block() -> dict:
    """The save-time topology: launch world size plus — when a mesh has
    been initialized — its axis degrees. Reads ``_GLOBAL_TOPO`` directly
    (never auto-initializes a mesh from inside a checkpoint save)."""
    block: Dict[str, Any] = {
        "world_size": int(os.environ.get("PADDLE_TRAINERS_NUM", "1")),
        "rank": int(os.environ.get("PADDLE_TRAINER_ID", "0")),
    }
    from . import mesh as _mesh
    topo = _mesh._GLOBAL_TOPO[0]
    if topo is not None:
        block["mesh"] = {k: int(v) for k, v in topo.dims.items()}
        block["axes"] = list(topo.AXES)
        block["devices"] = int(topo.world_size())
    return block


def sharding_specs(state) -> Optional[dict]:
    """Per-leaf ``{keystr: {shape, dtype, spec}}`` for every leaf of
    ``state`` carrying a NamedSharding (framework Tensors flatten to
    their jax arrays, so they are covered too)."""
    if state is None:
        return None
    import jax
    from jax.sharding import NamedSharding
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    specs: Dict[str, Any] = {}
    for path, leaf in flat:
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            specs[jax.tree_util.keystr(path)] = {
                "shape": [int(d) for d in leaf.shape],
                "dtype": str(np.dtype(leaf.dtype)),
                "spec": spec_to_json(sh.spec),
            }
    return specs or None


def rng_bundle() -> dict:
    """JSON-able snapshot of this rank's RNG streams: the framework
    default generator plus every named stream in the distributed
    RNGStatesTracker (dropout-in-TP-regions streams)."""
    from ..framework import random as frandom
    from . import random as drandom
    tracker = drandom.get_rng_state_tracker()
    return {
        "rank": int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        "framework": [int(x) for x in frandom.get_rng_state()],
        "tracker": {
            name: [int(x) for x in gen.get_state()]
            for name, gen in tracker.get_states_tracker().items()
        },
    }


def apply_rng_bundle(bundle: dict):
    """Restore the streams captured by :func:`rng_bundle`."""
    from ..framework import random as frandom
    from . import random as drandom
    fw = bundle.get("framework")
    if fw is not None:
        frandom.set_rng_state((int(fw[0]), int(fw[1])))
    tracker = drandom.get_rng_state_tracker()
    states: Dict[str, Any] = {}
    seeds = set()
    for name, st in (bundle.get("tracker") or {}).items():
        gen = frandom.Generator(int(st[0]))
        gen.set_state((int(st[0]), int(st[1])))
        states[name] = gen
        seeds.add(int(st[0]))
    if states or bundle.get("tracker") is not None:
        tracker.states_ = states
        tracker.seeds_ = seeds


def manifest_extra(data=None, rng: bool = True, state=None) -> dict:
    """The elastic-resume block CheckpointManager embeds in every commit
    manifest: topology, per-param shardings (when ``state`` is given),
    per-rank RNG streams, and the data-pipeline cursor (``data`` must
    expose ``state_dict``)."""
    extra: Dict[str, Any] = {"topology": topology_block()}
    if state is not None:
        try:
            specs = sharding_specs(state)
        except Exception:  # noqa: BLE001 — specs are advisory
            specs = None
        if specs:
            extra["shardings"] = specs
    if rng:
        extra["rng"] = rng_bundle()
    if data is not None:
        extra["data"] = data.state_dict()
    return extra


def apply_manifest_state(man: dict, *, data=None, rng: bool = False,
                         allow_version_skew: bool = False) -> dict:
    """Replay the manifest's data-pipeline cursor into ``data`` and (when
    ``rng=True``) its RNG streams into this process.

    RNG stream restore is version-sensitive (fold-in algorithms may
    change), so a framework-version mismatch between the checkpoint and
    this process raises :class:`~.fault_tolerance.VersionSkewError`
    unless ``allow_version_skew=True``. Returns ``{"data": bool, "rng":
    bool}`` saying what was actually applied."""
    applied = {"data": False, "rng": False}
    if data is not None and isinstance(man.get("data"), dict):
        if not hasattr(data, "load_state_dict"):
            raise TypeError(
                f"cannot replay data-pipeline state into "
                f"{type(data).__name__}: no load_state_dict")
        data.load_state_dict(man["data"])
        applied["data"] = True
    bundle = man.get("rng")
    if rng and isinstance(bundle, dict):
        from . import fault_tolerance as ft
        saved = man.get("framework_version")
        cur = ft._framework_version()
        if (saved not in (None, "unknown") and cur != "unknown"
                and saved != cur and not allow_version_skew):
            raise ft.VersionSkewError(
                f"checkpoint was written by paddle-tpu {saved} but this "
                f"process runs {cur}: restoring per-rank RNG streams "
                f"across versions can silently fork the dropout/data-aug "
                f"streams. Pass allow_version_skew=True to restore "
                f"anyway, or restore with apply_rng=False to reseed "
                f"fresh.")
        apply_rng_bundle(bundle)
        applied["rng"] = True
    return applied


def host_full(leaf) -> np.ndarray:
    """Full host array from a (possibly multi-process) ``jax.Array``
    using ONLY this process's addressable shards — no collectives, so it
    is safe on the failure path where peers may already be dead.

    Fully-addressable arrays (every single-process array, and replicated
    params in a gang) fetch directly. A cross-process array works iff
    this rank's shards cover the whole index space (replicated or
    batch-sharded-only leaves); a leaf whose data partly lives on a
    PEER process raises ``ValueError`` — that state is physically
    unrecoverable from one rank."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None or getattr(leaf, "is_fully_addressable", True):
        return np.asarray(leaf)
    out = np.empty(tuple(leaf.shape), dtype=leaf.dtype)
    covered = 0
    seen = set()
    for s in shards:
        data = np.asarray(s.data)
        out[s.index] = data
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        if key not in seen:
            seen.add(key)
            covered += data.size
    if covered < out.size:
        raise ValueError(
            f"array of shape {tuple(leaf.shape)} is not reconstructible "
            f"from this process's shards ({covered}/{out.size} elements "
            f"addressable): its sharding places data on peer processes")
    return out


# ---------------------------------------------------------------------------
# placement onto the current mesh
# ---------------------------------------------------------------------------

def _current_mesh(mesh=None):
    if mesh is not None:
        return mesh
    from . import mesh as _mesh
    m = _mesh.get_mesh()
    if m is None:
        m = _mesh.get_topology().mesh
    return m


def _rebind_spec(spec_json, mesh):
    """A saved spec re-bound to ``mesh``: axes the target mesh does not
    carry are dropped (those dims fall back to replicated there)."""
    have = set(mesh.axis_names)
    out = []
    for axes in (spec_json or []):
        kept = [a for a in (axes or []) if a in have]
        out.append(kept or None)
    return out


def place(host_array, spec_json, mesh=None):
    """Host array -> sharded jax.Array on the current mesh. Each device's
    callback cuts only that device's slice of the host buffer — the
    device-side cost of the restore is one transfer per local shard, not
    a full replicate-then-reshard."""
    import jax
    from jax.sharding import NamedSharding
    mesh = _current_mesh(mesh)
    arr = np.asarray(host_array)
    spec = spec_from_json(_rebind_spec(spec_json, mesh))
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])


def place_tree(host_tree, manifest: Optional[dict] = None, *, mesh=None,
               specs: Optional[dict] = None):
    """Re-place a host-loaded state tree onto the current mesh using the
    per-param specs saved in ``manifest["shardings"]`` (or an explicit
    ``specs`` map). Leaves without a recorded spec are placed replicated
    when they are arrays, left untouched otherwise."""
    import jax
    if specs is None:
        specs = (manifest or {}).get("shardings") or {}
    flat, treedef = jax.tree_util.tree_flatten_with_path(host_tree)
    out = []
    for path, leaf in flat:
        ent = specs.get(jax.tree_util.keystr(path))
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            spec_json = ent["spec"] if ent is not None else []
            out.append(place(leaf, spec_json, mesh=mesh))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def restore_resharded(root: str, step: Optional[int] = None, *,
                      state_file: str = "state.pdz", mesh=None,
                      data=None, rng: bool = False,
                      allow_version_skew: bool = False) -> Tuple[Any, int]:
    """Restore a committed ``root/step_N`` checkpoint written on ANY
    topology onto the current mesh: verify the manifest, materialize the
    full logical arrays host-side (pickle state file or orbax payload),
    then slice-and-place per the saved specs re-bound to this mesh.
    Optionally replays the data-pipeline cursor (``data=loader``) and
    per-rank RNG streams (``rng=True``) from the manifest.

    Returns ``(state, step)``; ``(None, 0)`` when ``root`` holds no
    committed step. The restored step is pinned as the keep-anchor so
    pruning cannot delete it while it is still the rewind target."""
    from . import fault_tolerance as ft
    root = os.path.abspath(root)
    if step is None:
        step = ft.latest_committed_step(root)
        if step is None:
            return None, 0
    d = os.path.join(root, ft.step_dir_name(step))
    man = ft.verify_dir(d)
    spath = os.path.join(d, state_file)
    if os.path.isfile(spath):
        from ..framework.io import load as fload
        host_state = fload(spath)
    else:
        # orbax payload: restore WITHOUT a target -> host numpy tree
        # (save-time placements may be unsatisfiable on this mesh)
        from . import checkpoint as dckpt
        host_state = dckpt.load(d, None, verify=False)  # verified above
    state = place_tree(host_state, man, mesh=mesh)
    ft.record_restore(step)
    apply_manifest_state(man, data=data, rng=rng,
                         allow_version_skew=allow_version_skew)
    ft.unpin_step(root)
    ft.pin_step(root, step)
    return state, step
