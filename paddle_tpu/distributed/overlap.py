"""Compute/communication overlap primitives.

Two latency-hiding mechanisms plus the static schedule model that
quantifies them:

1. **Per-layer gradient reduction in the backward pass**
   (``reduce_in_backward``): a custom_vjp identity whose transpose is a
   ``lax.psum``. Applied to each stacked-layer parameter slice inside
   ``run_layer_stack``'s scan body, it makes the transposed scan emit one
   gradient all-reduce *per layer, inside the backward loop* — layer L's
   reduction rides under layer L-1's backward matmuls — instead of the
   single fused tail all-reduce GSPMD schedules after the whole backward
   finishes. ``bucketed_psum`` plays the same role for the non-stacked
   tail parameters (embedding / norm / lm_head): several size-bounded
   collectives that can interleave with compute rather than one fused
   blob.

2. **Double-buffered pipeline p2p** (used by
   ``pipeline.pipeline_1f1b_value_and_grad(..., overlap=True)``): stage
   handoffs are issued a full tick ahead of the consuming compute, so
   within any tick the ppermute has no data dependence on that tick's
   forward/backward units and XLA's latency-hiding scheduler can overlap
   the ICI transfer with the matmuls. The schedule arithmetic lives here
   (``F_TICK``/``B_TICK``/``schedule_constants``) so the simulator below
   and the real scan body share one source of truth.

3. **Schedule simulator** (``schedule_events`` /
   ``transfer_stats`` / ``overlap_fraction``): a static, pure-Python
   event log of either schedule. Real async timing is not observable on
   the CPU backend, so the bench's ``overlap_fraction`` and the
   "serialized transfer→compute ticks" regression oracle both come from
   this model: a transfer whose consumer runs on the very next tick is
   *serialized* (it sits on the critical path between two compute
   ticks); a transfer with a full tick of slack is *overlapped*.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

__all__ = ["reduce_in_backward", "reduce_tree_in_backward", "bucketed_psum",
           "schedule_constants", "schedule_events", "transfer_stats",
           "overlap_fraction", "measured_overlap"]


# ---------------------------------------------------------------------------
# 1. async-dispatched gradient reduction
# ---------------------------------------------------------------------------

def _make_reduce_in_backward():
    import jax
    from jax import lax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def reduce_in_backward(x, axis_name):
        return x

    def _fwd(x, axis_name):
        return x, None

    def _bwd(axis_name, _res, g):
        return (lax.psum(g, axis_name),)

    reduce_in_backward.defvjp(_fwd, _bwd)
    return reduce_in_backward


_RIB = None


def reduce_in_backward(x, axis_name: str):
    """Identity in the forward pass; ``lax.psum(grad, axis_name)`` in the
    backward pass. Hooked onto a parameter *use site* inside a scanned
    layer body, the transpose emits the gradient all-reduce inside the
    backward scan — per-layer, overlapped with the remaining backward
    compute — rather than as one fused tail collective."""
    global _RIB
    if _RIB is None:
        _RIB = _make_reduce_in_backward()
    return _RIB(x, axis_name)


def reduce_tree_in_backward(tree, axis_name: str):
    """``reduce_in_backward`` applied to every leaf of a pytree."""
    import jax
    return jax.tree_util.tree_map(
        lambda a: reduce_in_backward(a, axis_name), tree)


def bucketed_psum(tree, axis_name: str, bucket_bytes: int = 4 << 20):
    """psum a pytree in size-bounded buckets: each bucket is one fused
    all-reduce, and separate buckets leave the compiler free to start
    reducing early buckets while later values are still being produced
    (fleet's DP gradient-bucketing, minus the streams). Leaf order is
    preserved."""
    import jax
    import numpy as np
    from jax import lax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    buckets: List[List[int]] = [[]]
    acc = 0
    for i, leaf in enumerate(leaves):
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize \
            if getattr(leaf, "shape", None) else leaf.dtype.itemsize
        if buckets[-1] and acc + nbytes > bucket_bytes:
            buckets.append([])
            acc = 0
        buckets[-1].append(i)
        acc += nbytes
    out = list(leaves)
    for idxs in buckets:
        reduced = lax.psum(tuple(leaves[i] for i in idxs), axis_name)
        for i, r in zip(idxs, reduced):
            out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# 2/3. 1F1B schedule arithmetic + static event model
# ---------------------------------------------------------------------------

def F_TICK(stage: int, micro: int, *, overlap: bool) -> int:
    """Tick at which stage ``stage`` runs the forward of microbatch
    ``micro``. Lockstep: s + m (handoffs consumed on the very next
    tick). Overlapped: 2s + m — one extra tick of pipeline depth per
    stage buys every edge transfer a full tick of slack."""
    return (2 * stage if overlap else stage) + micro


def B_TICK(stage: int, micro: int, pp: int, *, overlap: bool) -> int:
    """Tick of the backward unit B(stage, micro). Lockstep:
    2*pp - 1 - s + m. Overlapped: 4*(pp-1) + 1 - 2s + m (the last
    stage's backward still starts one tick after its forward)."""
    if overlap:
        return 4 * (pp - 1) + 1 - 2 * stage + micro
    return 2 * pp - 1 - stage + micro


def schedule_constants(pp: int, n_micro: int, *,
                       overlap: bool) -> Dict[str, int]:
    """(T, BUF) for the scan: total ticks and the stage-input ring-buffer
    depth. These are the same expressions the shard_map scan in
    ``pipeline.pipeline_1f1b_value_and_grad`` uses — the simulator and
    the kernel cannot drift apart."""
    if overlap:
        # last backward: B(0, n_micro-1) at 4*(pp-1)+1 + n_micro-1
        return {"T": n_micro + 4 * pp - 3, "BUF": 4 * pp}
    return {"T": n_micro + 2 * pp - 1, "BUF": 2 * pp}


def schedule_events(pp: int, n_micro: int, *, overlap: bool,
                    log: Optional[list] = None) -> List[Dict[str, Any]]:
    """Static event log of one 1F1B batch.

    Events (dicts) come in two kinds:
      compute  — {"kind": "fwd"|"bwd", "tick", "stage", "micro"}
      transfer — {"kind": "send_fwd"|"send_bwd", "tick", "src", "dst",
                  "micro", "produced_tick", "consumed_tick"}

    ``log`` is injectable: callers (tests) pass their own list and the
    function appends into it, so schedule-ordering assertions run
    against exactly what the model emitted. Returns the log either way.
    """
    if pp < 1 or n_micro < 1:
        raise ValueError(f"need pp >= 1 and n_micro >= 1, "
                         f"got pp={pp}, n_micro={n_micro}")
    events = log if log is not None else []
    for m in range(n_micro):
        for s in range(pp):
            tf = F_TICK(s, m, overlap=overlap)
            tb = B_TICK(s, m, pp, overlap=overlap)
            events.append({"kind": "fwd", "tick": tf, "stage": s,
                           "micro": m})
            events.append({"kind": "bwd", "tick": tb, "stage": s,
                           "micro": m})
            if s < pp - 1:
                # forward edge s -> s+1: consumed at F(s+1, m)
                consumed = F_TICK(s + 1, m, overlap=overlap)
                events.append({
                    "kind": "send_fwd", "micro": m, "src": s, "dst": s + 1,
                    "tick": tf + 1 if overlap else tf,
                    "produced_tick": tf, "consumed_tick": consumed})
            if s > 0:
                # backward edge s -> s-1: consumed at B(s-1, m)
                consumed = B_TICK(s - 1, m, pp, overlap=overlap)
                events.append({
                    "kind": "send_bwd", "micro": m, "src": s, "dst": s - 1,
                    "tick": tb + 1 if overlap else tb,
                    "produced_tick": tb, "consumed_tick": consumed})
    events.sort(key=lambda e: (e["tick"], e["stage"] if "stage" in e
                               else e["src"]))
    return events


def transfer_stats(events) -> Dict[str, int]:
    """Count stage-boundary transfers and how many are *serialized*: the
    consuming compute runs on the tick right after the producing compute,
    so the wire sits on the critical path (compute -> transfer ->
    compute with zero slack). A transfer with >= 2 ticks between
    producer and consumer has a full tick to ride under compute."""
    total = serialized = 0
    for e in events:
        if e["kind"] not in ("send_fwd", "send_bwd"):
            continue
        total += 1
        if e["consumed_tick"] - e["produced_tick"] < 2:
            serialized += 1
    return {"total_transfers": total, "serialized_transfers": serialized}


def overlap_fraction(events) -> float:
    """Fraction of stage-boundary transfers hidden under compute (1.0 =
    every edge has a free tick; 0.0 = every edge serializes a tick)."""
    st = transfer_stats(events)
    if st["total_transfers"] == 0:
        return 1.0
    return 1.0 - st["serialized_transfers"] / st["total_transfers"]


def measured_overlap(events) -> Dict[str, Any]:
    """Overlap report for a *recorded* schedule: feed it the event list
    ``profiler.trace.pipeline_schedule_events()`` returns (the flight
    recorder stores each scheduled unit verbatim in this module's event
    schema) and the exact simulator rules above score it — so a measured
    trace and ``schedule_events`` for the same (pp, n_micro, overlap)
    agree bit-for-bit, ordering included."""
    events = list(events)
    return {"transfer_stats": transfer_stats(events),
            "overlap_fraction": overlap_fraction(events),
            "n_events": len(events)}
