"""Sharded distributed checkpointing.

Reference analog: python/paddle/distributed/auto_parallel/dist_saver.py
(DistributedSaver.save/load — per-rank shard files ``model.pdmodel`` +
dist_attr manifests, manually re-merged and re-sliced when the restore
topology differs) and fleet's save_persistables
(python/paddle/distributed/fleet/fleet.py).

TPU-native: orbax writes ONE logical checkpoint for the whole mesh (every
host writes only its local shards, OCDBT/tensorstore format), and restore
re-shards to ANY target mesh/spec through the target tree's
NamedShardings — the reference's manual merge/re-slice pass collapses
into device_put-on-restore. Saving is async: the train loop keeps
stepping while shards stream out (``sync=False``).

Crash consistency (fault_tolerance module): every save streams into a
``*.ptq-tmp`` sibling, records a fsynced manifest (sizes + CRC32s +
step), and publishes with one atomic directory rename. Readers
(``latest_step`` / ``load`` / ``load_train_state``) only ever see
committed directories, verify the manifest before restoring, and fall
back to the previous committed step on corruption. Pruning never removes
the newest committed step and never touches a step an async save is
still writing.

Typical use with the flagship train step (models.llama.build_train_step):

    step_fn, init_fn = build_train_step(cfg, topo)
    params, opt_state = init_fn(rng)
    ...train...
    dckpt.save_train_state(ckdir, params, opt_state, step=1000)

    # later, on a DIFFERENT mesh shape:
    step_fn2, init_fn2 = build_train_step(cfg, topo2)
    target = init_fn2(rng)                      # placement donor
    params, opt_state, step = dckpt.load_train_state(ckdir, *target)
"""
from __future__ import annotations

import os
import shutil
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import jax

from . import fault_tolerance as ft
from ..testing.chaos import chaos_point

__all__ = ["save", "load", "save_step", "load_step", "save_train_state",
           "load_train_state", "latest_step", "abstract_like",
           "wait_until_finished", "CheckpointCorruptionError"]

CheckpointCorruptionError = ft.CheckpointCorruptionError

_CKPTR = None

# async commit machinery: each sync=False save hands its tmp->final
# publish to a waiter thread; wait_until_finished() joins them all, and
# pruning consults _INFLIGHT so a streaming step is never swept
_ASYNC_LOCK = threading.Lock()
_ASYNC_THREADS: List[threading.Thread] = []
_ASYNC_ERRORS: List[BaseException] = []
_INFLIGHT: Dict[str, Set[int]] = {}  # root -> steps still streaming


def _checkpointer():
    # module-level singleton: async saves (sync=False) stay awaitable via
    # wait_until_finished() instead of dying with a discarded local
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def wait_until_finished():
    """Block until every async save (sync=False) has committed. Raises
    the first deferred commit failure, if any."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()
    while True:
        with _ASYNC_LOCK:
            live = [t for t in _ASYNC_THREADS if t.is_alive()]
            if not live:
                _ASYNC_THREADS.clear()
                errs = list(_ASYNC_ERRORS)
                _ASYNC_ERRORS.clear()
                break
        for t in live:
            t.join()
    if errs:
        raise errs[0]


def abstract_like(tree):
    """Pytree of ShapeDtypeStructs carrying each leaf's sharding — the
    restore target that tells orbax where every shard of every array must
    land on the *current* mesh."""
    def conv(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        return x
    return jax.tree_util.tree_map(conv, tree)


# ---------------------------------------------------------------------------
# commit plumbing
# ---------------------------------------------------------------------------

def _finalize(tmp: str, final: str, t0: float, step: Optional[int],
              root: Optional[str], keep: Optional[int],
              extra: Optional[dict] = None):
    """Publish a durable tmp dir: manifest -> atomic rename -> metrics ->
    inflight bookkeeping -> pruning. Runs inline for sync saves, on the
    waiter thread for async ones (so pruning naturally waits on them)."""
    try:
        chaos_point("ckpt.commit.pre", step=step, path=final)
        if extra is None:
            extra = {"step": step} if step is not None else None
        man = ft.commit_dir(tmp, final, overwrite=True, extra=extra)
        chaos_point("ckpt.commit.post", step=step, path=final)
        ft.record_save(time.perf_counter() - t0, man["bytes_total"],
                       step=step)
    finally:
        if root is not None and step is not None:
            with _ASYNC_LOCK:
                _INFLIGHT.get(root, set()).discard(step)
    if root is not None and keep is not None:
        with _ASYNC_LOCK:
            inflight = set(_INFLIGHT.get(root, set()))
        ft.prune_steps(root, keep, inflight=inflight)


def _save_impl(final: str, tree: Any, *, overwrite: bool, sync: bool,
               step: Optional[int] = None, root: Optional[str] = None,
               keep: Optional[int] = None,
               extra: Optional[dict] = None) -> None:
    if os.path.exists(final) and not overwrite:
        raise FileExistsError(final)
    os.makedirs(os.path.dirname(final) or ".", exist_ok=True)
    tmp = final + ft.TMP_SUFFIX
    if os.path.exists(tmp):
        shutil.rmtree(tmp)  # stale leftover from a crashed save
    if root is not None and step is not None:
        with _ASYNC_LOCK:
            _INFLIGHT.setdefault(root, set()).add(step)
    chaos_point("ckpt.save.pre", step=step, path=final)
    t0 = time.perf_counter()
    ckptr = _checkpointer()
    try:
        ckptr.save(tmp, tree)
    except BaseException:
        if root is not None and step is not None:
            with _ASYNC_LOCK:
                _INFLIGHT.get(root, set()).discard(step)
        raise
    if sync:
        ckptr.wait_until_finished()
        _finalize(tmp, final, t0, step, root, keep, extra)
        return

    def _wait_and_commit():
        try:
            # waits for ALL pending orbax ops — ours included; a later
            # save's data becoming durable first is harmless
            _checkpointer().wait_until_finished()
            _finalize(tmp, final, t0, step, root, keep, extra)
        except BaseException as e:  # surfaced by wait_until_finished()
            with _ASYNC_LOCK:
                _ASYNC_ERRORS.append(e)

    th = threading.Thread(target=_wait_and_commit, daemon=True,
                          name="ptq-ckpt-commit")
    with _ASYNC_LOCK:
        _ASYNC_THREADS.append(th)
    th.start()


def save(path: str, tree: Any, *, overwrite: bool = True,
         sync: bool = True) -> None:
    """Save a pytree of (sharded) arrays as one logical checkpoint.
    Crash-consistent: the previous checkpoint at ``path`` survives until
    the replacement has fully committed."""
    _save_impl(os.path.abspath(path), tree, overwrite=overwrite, sync=sync)


def load(path: str, target: Any = None, *, verify: bool = True) -> Any:
    """Restore a checkpoint. ``target`` (a tree of arrays or
    ShapeDtypeStructs) dictates shapes/dtypes/shardings on the current
    mesh — pass the init_fn output of the new topology to reshard; omit it
    to restore with the shardings recorded at save time.

    Only committed checkpoints are visible: a half-written directory is
    recovered to the last committed copy or rejected, and ``verify=True``
    checks the manifest (sizes + CRC32s) before any deserialization."""
    path = ft.recover_dir(os.path.abspath(path))
    if verify:
        ft.verify_dir(path)
    ckptr = _checkpointer()
    if target is None:
        return ckptr.restore(path)
    return ckptr.restore(path, abstract_like(target))


# ---------------------------------------------------------------------------
# step-directory train-state API
# ---------------------------------------------------------------------------

def latest_step(root: str) -> Optional[int]:
    """Newest COMMITTED step under ``root`` — never a half-written
    ``step_*`` directory."""
    return ft.latest_committed_step(root)


def _step_dir(root: str, step: int) -> str:
    return os.path.join(os.path.abspath(root), ft.step_dir_name(step))


def save_step(root: str, state: Any, step: int, *, keep: int = 3,
              sync: bool = True, extra: Optional[dict] = None) -> str:
    """Save an arbitrary pytree under root/step_N with the commit
    protocol, pruning old committed steps (keep=0 keeps all). Pruning
    skips steps still streaming in async saves and never removes the
    newest committed step. ``extra`` replaces the default ``{"step"}``
    manifest extras (topology/RNG/data state from CheckpointManager)."""
    root_abs = os.path.abspath(root)
    d = _step_dir(root, step)
    _save_impl(d, state, overwrite=True, sync=sync, step=step,
               root=root_abs, keep=keep, extra=extra)
    return d


def load_step(root: str, target: Any = None, step: Optional[int] = None,
              ) -> Tuple[Any, int]:
    """(state, step) from ``root`` — the requested step, or the newest
    committed one, falling back past corrupt steps (each fallback
    increments ``ckpt_restore_fallback_total``)."""
    if step is not None:
        state = load(_step_dir(root, step), target)
        ft.record_restore(step)
        return state, step
    steps = ft.committed_steps(root)
    if not steps:
        raise FileNotFoundError(
            f"no committed step_* checkpoints under {root}")
    for s in reversed(steps):
        try:
            state = load(_step_dir(root, s), target)
        except (ft.CheckpointCorruptionError, FileNotFoundError) as e:
            sys.stderr.write(
                f"checkpoint: step {s} under {root} failed verification "
                f"({e}); falling back to the previous committed step\n")
            ft.record_fallback(s)
            continue
        ft.record_restore(s)
        return state, s
    raise ft.CheckpointCorruptionError(
        f"every committed step under {root} failed verification "
        f"(tried {list(reversed(steps))})")


def save_train_state(root: str, params: Any, opt_state: Any, step: int,
                     *, keep: int = 3, sync: bool = True) -> str:
    """Save (params, opt_state) under root/step_N, pruning old steps."""
    return save_step(root, {"params": params, "opt_state": opt_state},
                     step, keep=keep, sync=sync)


def load_train_state(root: str, params_target: Any = None,
                     opt_state_target: Any = None,
                     step: Optional[int] = None
                     ) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state, step) from root (latest committed
    step unless given), resharded onto the targets' placements."""
    if (params_target is None) != (opt_state_target is None):
        raise ValueError(
            "pass both params_target and opt_state_target (the restore "
            "target must cover the whole saved state) or neither")
    target = None
    if params_target is not None:
        target = {"params": params_target, "opt_state": opt_state_target}
    state, got = load_step(root, target, step=step)
    return state["params"], state["opt_state"], got
