"""Sharded distributed checkpointing.

Reference analog: python/paddle/distributed/auto_parallel/dist_saver.py
(DistributedSaver.save/load — per-rank shard files ``model.pdmodel`` +
dist_attr manifests, manually re-merged and re-sliced when the restore
topology differs) and fleet's save_persistables
(python/paddle/distributed/fleet/fleet.py).

TPU-native: orbax writes ONE logical checkpoint for the whole mesh (every
host writes only its local shards, OCDBT/tensorstore format), and restore
re-shards to ANY target mesh/spec through the target tree's
NamedShardings — the reference's manual merge/re-slice pass collapses
into device_put-on-restore. Saving is async: the train loop keeps
stepping while shards stream out (``sync=False``).

Typical use with the flagship train step (models.llama.build_train_step):

    step_fn, init_fn = build_train_step(cfg, topo)
    params, opt_state = init_fn(rng)
    ...train...
    dckpt.save_train_state(ckdir, params, opt_state, step=1000)

    # later, on a DIFFERENT mesh shape:
    step_fn2, init_fn2 = build_train_step(cfg, topo2)
    target = init_fn2(rng)                      # placement donor
    params, opt_state, step = dckpt.load_train_state(ckdir, *target)
"""
from __future__ import annotations

import os
import re
import shutil
from typing import Any, Optional, Tuple

import jax

__all__ = ["save", "load", "save_train_state", "load_train_state",
           "latest_step", "abstract_like", "wait_until_finished"]

_STEP_RE = re.compile(r"^step_(\d+)$")

_CKPTR = None


def _checkpointer():
    # module-level singleton: async saves (sync=False) stay awaitable via
    # wait_until_finished() instead of dying with a discarded local
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def wait_until_finished():
    """Block until every async save (sync=False) has committed."""
    if _CKPTR is not None:
        _CKPTR.wait_until_finished()


def abstract_like(tree):
    """Pytree of ShapeDtypeStructs carrying each leaf's sharding — the
    restore target that tells orbax where every shard of every array must
    land on the *current* mesh."""
    def conv(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        return x
    return jax.tree_util.tree_map(conv, tree)


def save(path: str, tree: Any, *, overwrite: bool = True,
         sync: bool = True) -> None:
    """Save a pytree of (sharded) arrays as one logical checkpoint."""
    path = os.path.abspath(path)
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    ckptr = _checkpointer()
    ckptr.save(path, tree)
    if sync:
        ckptr.wait_until_finished()


def load(path: str, target: Any = None) -> Any:
    """Restore a checkpoint. ``target`` (a tree of arrays or
    ShapeDtypeStructs) dictates shapes/dtypes/shardings on the current
    mesh — pass the init_fn output of the new topology to reshard; omit it
    to restore with the shardings recorded at save time."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    if target is None:
        return ckptr.restore(path)
    return ckptr.restore(path, abstract_like(target))


def latest_step(root: str) -> Optional[int]:
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        return None
    steps = [int(m.group(1)) for d in os.listdir(root)
             if (m := _STEP_RE.match(d))]
    return max(steps) if steps else None


def _step_dir(root: str, step: int) -> str:
    return os.path.join(os.path.abspath(root), f"step_{step:08d}")


def save_train_state(root: str, params: Any, opt_state: Any, step: int,
                     *, keep: int = 3, sync: bool = True) -> str:
    """Save (params, opt_state) under root/step_N, pruning old steps."""
    d = _step_dir(root, step)
    save(d, {"params": params, "opt_state": opt_state}, sync=sync)
    steps = sorted(int(m.group(1)) for x in os.listdir(os.path.abspath(root))
                   if (m := _STEP_RE.match(x)))
    for s in steps[:-keep] if keep else []:
        shutil.rmtree(_step_dir(root, s), ignore_errors=True)
    return d


def load_train_state(root: str, params_target: Any = None,
                     opt_state_target: Any = None,
                     step: Optional[int] = None
                     ) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state, step) from root (latest step unless
    given), resharded onto the targets' placements."""
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no step_* checkpoints under {root}")
    if (params_target is None) != (opt_state_target is None):
        raise ValueError(
            "pass both params_target and opt_state_target (the restore "
            "target must cover the whole saved state) or neither")
    target = None
    if params_target is not None:
        target = {"params": params_target, "opt_state": opt_state_target}
    state = load(_step_dir(root, step), target)
    return state["params"], state["opt_state"], step
