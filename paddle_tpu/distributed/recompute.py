"""Activation recompute (gradient checkpointing).

Reference analog: python/paddle/distributed/fleet/recompute/recompute.py
(`RecomputeFunction(PyLayer)` at :69 — drops activations in forward, replays
the forward inside backward with preserved RNG state) and
recompute_hybrid.py (the hybrid-parallel variant that additionally
partitions saved activations over the mp group).

TPU-native design: `jax.checkpoint` (remat) IS the recompute engine — the
wrapped computation is re-traced into the backward pass and XLA schedules
the replay, so there is no PyLayer, no RNG stashing (the RNG keys consumed
by dropout etc. are *inputs* to the traced computation; the remat replay
re-executes the identical jaxpr with identical keys, which is what
`preserve_rng_state=True` means in the reference), and no manual activation
partitioning (saved residuals inherit the sharding of the live values).

The eager-facade integration: gradients must flow not only to the explicit
tensor arguments but to the parameters the wrapped callable closes over
(the reference gets this for free from its global autograd graph). We lift
closed-over `Layer` parameters into explicit inputs of the rematerialised
function so the tape records them as edges — including layers reachable
through plain-function closures (the `create_custom_forward(block)` paddle
idiom).
"""
from __future__ import annotations

from typing import Any, Callable, Sequence

import jax

from ..core.tensor import Tensor, apply_op, no_grad, _as_array

__all__ = ["recompute", "recompute_sequential"]


def _closure_params(function: Callable):
    """Trainable parameters reachable from the callable: a Layer, a bound
    method of a Layer, or a plain function/lambda whose closure cells (or
    defaults) hold Layers/parameters — the common
    `recompute(create_custom_forward(block), x)` pattern."""
    from ..nn.layer.layers import Layer

    seen_params = {}
    seen_objs = set()

    def visit(obj, depth=0):
        if obj is None or id(obj) in seen_objs or depth > 3:
            return
        seen_objs.add(id(obj))
        if isinstance(obj, Layer):
            for p in obj.parameters():
                if not p.stop_gradient:
                    seen_params.setdefault(id(p), p)
        elif isinstance(obj, Tensor):
            if not obj.stop_gradient:
                seen_params.setdefault(id(obj), obj)
        elif callable(obj):
            visit(getattr(obj, "__self__", None), depth + 1)
            for cell in (getattr(obj, "__closure__", None) or ()):
                try:
                    visit(cell.cell_contents, depth + 1)
                except ValueError:  # empty cell
                    pass
            for d in (getattr(obj, "__defaults__", None) or ()):
                visit(d, depth + 1)
        elif isinstance(obj, (list, tuple)):
            for item in obj:
                visit(item, depth + 1)

    visit(function)
    return list(seen_params.values())


def _recompute_impl(function: Callable, params, args, kwargs):
    """Single implementation: lift every Tensor in (args, kwargs) — however
    deeply nested in containers — plus the closed-over params into inputs of
    a jax.checkpoint-wrapped pure function and route through the tape."""
    is_tensor = lambda x: isinstance(x, Tensor)  # noqa: E731
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                 is_leaf=is_tensor)
    tensor_idx = [i for i, x in enumerate(leaves) if isinstance(x, Tensor)]
    tensor_args = [leaves[i] for i in tensor_idx]
    n_args = len(tensor_args)

    def of_arrays(*arrays):
        arg_arrays, param_arrays = arrays[:n_args], arrays[n_args:]
        new_leaves = list(leaves)
        for i, arr in zip(tensor_idx, arg_arrays):
            new_leaves[i] = Tensor(arr)
        r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, new_leaves)
        saved = [p._array for p in params]
        for p, arr in zip(params, param_arrays):
            p._array = arr
        try:
            with no_grad():
                out = function(*r_args, **r_kwargs)
        finally:
            for p, arr in zip(params, saved):
                p._array = arr
        if isinstance(out, (tuple, list)):
            return tuple(_as_array(o) for o in out)
        return _as_array(out)

    remat_fn = jax.checkpoint(of_arrays)
    return apply_op(lambda *a: remat_fn(*[_as_array(x) for x in a]),
                    *tensor_args, *params, op_name="recompute")


def recompute(function: Callable, *args, **kwargs):
    """Run `function(*args, **kwargs)` without keeping its intermediate
    activations; they are rematerialised during backward.

    reference: fleet/recompute/recompute.py:69 (RecomputeFunction) and the
    public `paddle.distributed.fleet.utils.recompute`.

    `preserve_rng_state` is accepted for API parity and is always
    effectively True (see module docstring); `use_reentrant` is accepted
    and ignored (there is a single implementation).
    """
    kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", True)
    return _recompute_impl(function, _closure_params(function), args, kwargs)


def recompute_sequential(ctx: dict, functions: Sequence[Callable], *args):
    """Checkpoint a sequence of layers in `segments` chunks
    (reference: later paddle's recompute_sequential; provided here because
    segment-wise remat is the natural granularity on TPU — each segment
    becomes one remat region XLA can schedule independently)."""
    ctx = ctx or {}
    segments = int(ctx.get("segments", 1))
    functions = list(functions)
    n = len(functions)
    seg = max(1, -(-n // max(1, segments)))  # ceil: at most `segments` chunks

    def make_chunk(fns):
        def chunk(*xs):
            out = xs
            for f in fns:
                out = f(*out) if isinstance(out, tuple) else f(out)
            return out
        return chunk

    out: Any = args
    for start in range(0, n, seg):
        fns = functions[start:start + seg]
        params: list = []
        pid = set()
        for f in fns:
            for p in _closure_params(f):
                if id(p) not in pid:
                    pid.add(id(p))
                    params.append(p)
        out_t = out if isinstance(out, tuple) else (out,)
        out = _recompute_impl(make_chunk(fns), params, out_t, {})
    return out
