"""TCPStore — rendezvous key-value store for multi-host bootstrap.

Reference analog: phi::distributed::TCPStore
(paddle/phi/core/distributed/store/tcp_store.h:117) used by ProcessGroup
creation to exchange NCCL unique ids. Here it bootstraps
jax.distributed-style coordination and carries small rendezvous blobs
(coordinator address, per-rank host info). Backed by the native C++
server/client (csrc/tcp_store.cc); a pure-Python fallback covers
toolchain-free environments.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Optional

from ..core import native
from ..testing.chaos import chaos_point

__all__ = ["TCPStore"]

# transient client-side failures worth retrying: connection drops and
# generic socket I/O errors (the native wrapper surfaces them as
# IOError). TimeoutError — although an OSError subclass — means the
# server-side budget expired and retrying would double it, so it is in
# the give-up set, as are programming errors.
_TRANSIENT = (ConnectionError, OSError)
_GIVE_UP = (TimeoutError,)


class _NativeStore:
    def __init__(self, host, port, is_master, timeout):
        L = native.lib()
        self._lib = L
        self._server = None
        # ONE socket per client: every op is a request/response exchange
        # on it, so concurrent callers (main thread + health-monitor
        # beats + fleet heartbeat thread in a gang worker) must be
        # serialized or the framing interleaves — the observed failure
        # is a garbled length prefix read as a huge allocation size
        self._oplock = threading.Lock()
        import ctypes
        if is_master:
            out_port = ctypes.c_int(0)
            self._server = L.ptq_store_server_start(
                port, ctypes.byref(out_port))
            if not self._server:
                raise OSError(f"TCPStore server failed to bind :{port}")
            port = out_port.value
        self.port = port
        ip = socket.gethostbyname(host)
        self._h = L.ptq_store_connect(ip.encode(), port,
                                      int(timeout * 1000))
        if not self._h:
            raise TimeoutError(f"TCPStore connect to {host}:{port} failed")

    def set(self, key: str, value: bytes):
        with self._oplock:
            rc = self._lib.ptq_store_set(self._h, key.encode(), value,
                                         len(value))
        if rc < 0:
            raise IOError("TCPStore.set failed")

    def _get(self, fn, key):
        import ctypes
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            with self._oplock:
                n = fn(self._h, key.encode(), buf, cap)
            if n == -2:
                cap *= 16
                continue
            if n < 0:
                return None
            return buf.raw[:n]

    def get(self, key: str) -> Optional[bytes]:
        return self._get(self._lib.ptq_store_get, key)

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        if timeout is None:
            # server-side blocking wait: returns when the key lands
            out = self._get(self._lib.ptq_store_wait, key)
            if out is None:
                raise TimeoutError(f"TCPStore.wait({key!r}) aborted")
            return out
        # bounded wait: poll `get` against a local deadline instead of
        # abandoning a blocking wait mid-reply (which would desync the
        # connection's request/response framing)
        deadline = time.monotonic() + timeout
        poll_s = 0.02
        while True:
            out = self._get(self._lib.ptq_store_get, key)
            if out is not None:
                return out
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"TCPStore.wait({key!r}) timed out after {timeout:.1f}s")
            time.sleep(min(poll_s, remaining))
            poll_s = min(poll_s * 2, 0.25)

    def add(self, key: str, delta: int = 1) -> int:
        with self._oplock:
            v = self._lib.ptq_store_add(self._h, key.encode(), delta)
        if v == -(2 ** 63):
            raise IOError("TCPStore.add failed")
        return int(v)

    def delete(self, key: str) -> bool:
        with self._oplock:
            return self._lib.ptq_store_delete(self._h, key.encode()) > 0

    def close(self):
        with self._oplock:
            if self._h:
                self._lib.ptq_store_disconnect(self._h)
                self._h = None
            if self._server:
                self._lib.ptq_store_server_stop(self._server)
                self._server = None


class _PyStore:
    """In-process fallback with the same surface (single-host only)."""

    _GLOBAL = {}
    _LOCK = threading.Lock()
    _CV = threading.Condition(_LOCK)

    def __init__(self, host, port, is_master, timeout):
        self.port = port
        self.timeout = timeout  # store-level default honored by wait()

    def set(self, key, value):
        with self._CV:
            self._GLOBAL[key] = value
            self._CV.notify_all()

    def get(self, key):
        with self._LOCK:
            return self._GLOBAL.get(key)

    def wait(self, key, timeout=None):
        if timeout is None:
            timeout = self.timeout
        with self._CV:
            ok = self._CV.wait_for(lambda: key in self._GLOBAL, timeout)
            if not ok:
                raise TimeoutError(
                    f"TCPStore.wait({key!r}) timed out after {timeout:.1f}s")
            return self._GLOBAL[key]

    def add(self, key, delta=1):
        with self._CV:
            cur = int(self._GLOBAL.get(key, b"0")) + delta
            self._GLOBAL[key] = str(cur).encode()
            self._CV.notify_all()
            return cur

    def delete(self, key):
        with self._LOCK:
            return self._GLOBAL.pop(key, None) is not None

    def close(self):
        pass


class TCPStore:
    """paddle-compatible surface: TCPStore(host, port, is_master,
    world_size, timeout). Values are bytes; helpers for python objects.

    Client ``get``/``set``/``add`` retry transient socket failures with
    bounded exponential backoff + jitter (a preempted master restarting,
    a dropped connection mid-rendezvous); non-transient errors and
    timeouts raise immediately. ``_sleep``/``_retry_rng`` are injectable
    so tests can assert the schedule without real waiting."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 300.0, retries: int = 4,
                 retry_base_delay: float = 0.05,
                 retry_max_delay: float = 2.0):
        self.host = host
        self.world_size = world_size
        self.timeout = float(timeout)  # default budget for wait()
        if native.available():
            self._impl = _NativeStore(host, port, is_master, timeout)
        else:
            self._impl = _PyStore(host, port, is_master, timeout)
        self.port = self._impl.port
        self.is_native = isinstance(self._impl, _NativeStore)
        self.retries = int(os.environ.get("PTQ_STORE_RETRIES", retries))
        self.retry_base_delay = retry_base_delay
        self.retry_max_delay = retry_max_delay
        self._sleep = time.sleep
        self._retry_rng = None  # None -> fresh jitter per call chain

    def _with_retries(self, what: str, fn):
        from .fault_tolerance import retry_with_backoff

        def _on_retry(attempt, exc, delay):
            import sys
            sys.stderr.write(
                f"TCPStore.{what}: transient failure ({exc}); retry "
                f"{attempt}/{self.retries - 1} in {delay:.2f}s\n")
            from ..profiler import metrics
            if metrics.enabled():
                metrics.counter("store_retry_total",
                                "TCPStore transient-error retries",
                                op=what).inc()

        return retry_with_backoff(
            fn, retryable=_TRANSIENT, give_up=_GIVE_UP,
            attempts=self.retries, base_delay=self.retry_base_delay,
            max_delay=self.retry_max_delay, sleep=self._sleep,
            rng=self._retry_rng, on_retry=_on_retry)

    def set(self, key: str, value) -> None:
        if not isinstance(value, (bytes, bytearray)):
            value = pickle.dumps(value)
        data = bytes(value)

        def _op():
            chaos_point("store.set", path=None, key=key)
            self._impl.set(key, data)
        self._with_retries("set", _op)

    def get(self, key: str) -> Optional[bytes]:
        def _op():
            chaos_point("store.get", path=None, key=key)
            return self._impl.get(key)
        return self._with_retries("get", _op)

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        """Block until ``key`` exists, up to ``timeout`` (default: the
        store-level ``TCPStore(timeout=...)`` value). Raises
        ``TimeoutError`` with identical semantics on both backends."""
        chaos_point("store.wait", path=None, key=key)
        return self._impl.wait(
            key, self.timeout if timeout is None else timeout)

    def get_obj(self, key: str, timeout: Optional[float] = None):
        raw = self.wait(key, timeout)
        return pickle.loads(raw)

    def add(self, key: str, delta: int = 1) -> int:
        def _op():
            chaos_point("store.add", path=None, key=key)
            return self._impl.add(key, delta)
        return self._with_retries("add", _op)

    def delete_key(self, key: str) -> bool:
        return self._impl.delete(key)

    def barrier(self, name: str = "barrier", rank: Optional[int] = None,
                poll_s: float = 0.01, timeout: Optional[float] = None):
        """All world_size ranks block until everyone arrived.

        Each rank stamps a per-rank arrival key before bumping the
        shared counter, so a timeout can NAME the ranks that never
        showed up (the one diagnostic that matters when a pod wedges at
        rendezvous) instead of raising a bare TimeoutError. ``rank``
        defaults to ``PADDLE_TRAINER_ID`` — the launcher sets it in
        every worker."""
        if rank is None:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.set(f"__bar_in__{name}/{rank}", b"1")
        n = self.add(f"__bar__{name}", 1)
        if n == self.world_size:
            self.set(f"__bar_done__{name}", b"1")
        try:
            self.wait(f"__bar_done__{name}", timeout)
        except TimeoutError:
            missing = self.barrier_missing(name)
            budget = self.timeout if timeout is None else timeout
            from ..runtime.watchdog import record_incident
            record_incident("store_barrier_timeout", barrier=name,
                            rank=rank, world_size=self.world_size,
                            timeout_s=round(float(budget), 3),
                            missing=missing)
            raise TimeoutError(
                f"store barrier {name!r} timed out after {budget:.1f}s: "
                f"rank {rank} waited for {self.world_size} ranks but "
                f"ranks {missing} never arrived") from None

    def barrier_missing(self, name: str) -> list:
        """Ranks with no arrival stamp for barrier ``name`` (diagnostic
        read — best-effort, never raises)."""
        missing = []
        for r in range(self.world_size):
            try:
                if self.get(f"__bar_in__{name}/{r}") is None:
                    missing.append(r)
            except Exception:  # tpu-lint: disable=except-pass
                missing.append(r)
        return missing

    def close(self):
        self._impl.close()

    def __del__(self):
        try:
            self.close()
        # genuinely best-effort: __del__ runs during interpreter
        # teardown where sockets/modules may already be gone
        except Exception:  # tpu-lint: disable=except-pass
            pass
