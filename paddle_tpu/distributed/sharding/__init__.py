"""ZeRO-style sharded data parallel.

Reference analog: python/paddle/distributed/sharding/group_sharded.py:37
(group_sharded_parallel levels os/os_g/p_g_os) over
fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py.

TPU-native: ZeRO is a *placement decision*, not a runtime:
- stage 1 (os):    optimizer accumulators sharded over the 'sharding' axis;
- stage 2 (os_g):  + gradients reduce-scattered (GSPMD emits reduce-scatter
                   when grad outputs are marked sharded);
- stage 3 (p_g_os):+ parameters sharded, all-gathered per use (GSPMD emits
                   the gathers from the param shardings).
`group_sharded_parallel` annotates parameters; a jit'd train step realizes
the placement through its in/out shardings (see
models.llama.build_train_step for the flagship example).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec

import numpy as np

from ...nn.layer.layers import Layer
from ..mesh import get_topology

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "zero_spec_for_param"]


def zero_spec_for_param(p, axis="sharding", min_size=1024):
    """Choose the ZeRO partition spec for a flat param/accumulator: shard
    the largest divisible dim over `axis` (the reference slices flattened
    params; sharding a real dim keeps XLA layouts intact)."""
    topo = get_topology()
    n = topo.dims.get(axis, 1) if topo else 1
    if n <= 1 or int(np.prod(p.shape or [1])) < min_size:
        return PartitionSpec()
    existing = getattr(p, "sharding_spec", None)
    taken = set(existing) if existing else set()
    dims = [None] * len(p.shape)
    if existing:
        dims = list(existing) + [None] * (len(p.shape) - len(existing))
    for i, d in sorted(enumerate(p.shape), key=lambda t: -t[1]):
        if dims[i] is None and d % n == 0:
            dims[i] = axis
            return PartitionSpec(*dims)
    return PartitionSpec(*dims)


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    assert level in ("os", "os_g", "p_g_os"), level
    for _, p in model.named_parameters():
        spec = zero_spec_for_param(p)
        p.opt_state_spec = spec                 # stage >=1: optimizer state
        p.grad_spec = spec if level in ("os_g", "p_g_os") \
            else getattr(p, "sharding_spec", None)
        if level == "p_g_os":
            # parameter itself sharded; merge with any TP spec
            p.sharding_spec = spec if getattr(p, "sharding_spec", None) \
                is None else p.sharding_spec
    model._sharding_level = level
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    from ...framework.io import save
    save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
