"""Framework metrics registry: Counter / Gauge / Histogram.

Reference analog: the reference stack surfaces framework counters through
profiler_statistic tables and external exporters; production TPU serving
(MPK / Gemma-on-TPU serving writeups in PAPERS.md) standardizes on a
Prometheus-style pull registry. This module is that registry for
paddle_tpu: process-global, thread-safe, and cheap enough to leave the
call sites compiled into every hot path.

Gating contract (ROADMAP "as fast as the hardware allows"): every
recording call first runs `enabled()` — one dict lookup plus a boolean
check against the ``FLAGS_tpu_metrics`` flag — and returns immediately
when metrics are off. No locks, no allocation, no string formatting on
the disabled path. Call sites that need to skip even argument
construction should guard with ``if metrics.enabled():`` themselves.

Exports: `snapshot()` (plain dict), `to_json()`, and `to_prometheus()`
(text exposition format 0.0.4) so a sidecar can scrape a training job
without attaching xprof.
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, List, Optional, Tuple

from ..core import flags as _flags

__all__ = ["Counter", "Gauge", "Histogram", "enabled", "counter", "gauge",
           "histogram", "snapshot", "to_json", "to_prometheus", "reset",
           "DEFAULT_BUCKETS"]

# direct reference to the flag registry dict: enabled() must cost one
# dict lookup + bool check, never a function-call chain through get_flags
_FLAG_DICT = _flags._REGISTRY
_FLAG_NAME = "FLAGS_tpu_metrics"


def enabled() -> bool:
    """Whether metric recording is on (the only check hot paths pay)."""
    return bool(_FLAG_DICT.get(_FLAG_NAME, False))


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _format_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_str: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_str
        self.labels = _label_key(labels or {})
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonically increasing count (calls, bytes, retraces...)."""

    kind = "counter"

    def __init__(self, name, help_str="", labels=None):
        super().__init__(name, help_str, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if not enabled():
            return
        if amount < 0:
            raise ValueError(f"Counter {self.name} cannot decrease")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def _snapshot(self):
        return self._value


class Gauge(_Metric):
    """Point-in-time value (queue depth, cache size, live workers)."""

    kind = "gauge"

    def __init__(self, name, help_str="", labels=None):
        super().__init__(name, help_str, labels)
        self._value = 0.0

    def set(self, value: float):
        if not enabled():
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        if not enabled():
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def _snapshot(self):
        return self._value


# latency-oriented default: 100us .. ~100s, roughly x3 per step
DEFAULT_BUCKETS = (1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3,
                   1.0, 3.0, 10.0, 30.0, 100.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram with count/sum/max and approximate
    percentiles (read off the bucket CDF, reported as the bucket's
    upper bound — the Prometheus `histogram_quantile` convention)."""

    kind = "histogram"

    def __init__(self, name, help_str="", labels=None, buckets=None):
        super().__init__(name, help_str, labels)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf
        self._count = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, value: float):
        if not enabled():
            return
        with self._lock:
            self._count += 1
            self._sum += value
            if value > self._max:
                self._max = value
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def max(self) -> float:
        return self._max

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100])."""
        with self._lock:
            if self._count == 0:
                return 0.0
            rank = math.ceil(self._count * q / 100.0)
            cum = 0
            for i, ub in enumerate(self.buckets):
                cum += self._counts[i]
                if cum >= rank:
                    return ub
            return self._max  # landed in the +Inf bucket

    def _snapshot(self):
        return {"count": self._count, "sum": self._sum, "max": self._max,
                "avg": self._sum / self._count if self._count else 0.0,
                "p50": self.percentile(50), "p95": self.percentile(95)}


class MetricRegistry:
    """Process-global name->(labelset->metric) store."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], _Metric] = {}

    def _get_or_create(self, cls, name, help_str, labels, **kw):
        key = (name, _label_key(labels or {}))
        m = self._metrics.get(key)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help_str, labels, **kw)
                self._metrics[key] = m
            return m

    def counter(self, name, help_str="", **labels) -> Counter:
        return self._get_or_create(Counter, name, help_str, labels)

    def gauge(self, name, help_str="", **labels) -> Gauge:
        return self._get_or_create(Gauge, name, help_str, labels)

    def histogram(self, name, help_str="", buckets=None,
                  **labels) -> Histogram:
        return self._get_or_create(Histogram, name, help_str, labels,
                                   buckets=buckets)

    def snapshot(self) -> dict:
        """Plain-dict view: name -> value, or name{labels} -> value for
        labeled series; histograms expand to a stats sub-dict."""
        out = {}
        with self._lock:
            items = list(self._metrics.items())
        for (name, labels), m in items:
            out[name + _format_labels(labels)] = m._snapshot()
        return out

    def to_json(self, **dump_kwargs) -> str:
        return json.dumps(self.snapshot(), sort_keys=True, **dump_kwargs)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            items = list(self._metrics.items())
        by_name: Dict[str, List[Tuple[Tuple, _Metric]]] = {}
        for (name, labels), m in items:
            by_name.setdefault(name, []).append((labels, m))
        lines: List[str] = []
        for name in sorted(by_name):
            series = by_name[name]
            kind = series[0][1].kind
            help_str = next((m.help for _, m in series if m.help), "")
            if help_str:
                lines.append(f"# HELP {name} {help_str}")
            lines.append(f"# TYPE {name} {kind}")
            for labels, m in sorted(series, key=lambda s: s[0]):
                if isinstance(m, Histogram):
                    cum = 0
                    for i, ub in enumerate(m.buckets):
                        cum += m._counts[i]
                        lbl = _format_labels(labels + (("le", repr(ub)),))
                        lines.append(f"{name}_bucket{lbl} {cum}")
                    lbl = _format_labels(labels + (("le", "+Inf"),))
                    lines.append(f"{name}_bucket{lbl} {m._count}")
                    lines.append(
                        f"{name}_sum{_format_labels(labels)} {m._sum}")
                    lines.append(
                        f"{name}_count{_format_labels(labels)} {m._count}")
                else:
                    lines.append(
                        f"{name}{_format_labels(labels)} {m._value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Drop all metrics (tests / between benchmark cases)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricRegistry()

# module-level conveniences bound to the global registry
counter = _REGISTRY.counter
gauge = _REGISTRY.gauge
histogram = _REGISTRY.histogram
snapshot = _REGISTRY.snapshot
to_json = _REGISTRY.to_json
to_prometheus = _REGISTRY.to_prometheus
reset = _REGISTRY.reset
