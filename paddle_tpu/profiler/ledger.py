"""Provenance-stamped perf ledger: schema, registry, gate and report.

The ledger is the repo's durable perf history: one append-only JSONL file
(``PERF_LEDGER.jsonl`` at the repo root) where every bench artifact we emit
— ``bench.py`` lines, ``bench_serve.py`` lines, driver ``BENCH_r0*.json`` /
``MULTICHIP_r0*.json`` wrappers, ``fleet_sim`` reports and ``pod_report``
verdicts — is normalized into a single schema-versioned row:

    {"schema": "paddle_tpu.perf_ledger.v1",
     "round": 6, "ts": null, "source": "bench.py --multichip",
     "kind": "measured",              # measured | proxy | error
     "label": "",                     # series separator within a source
     "metrics": {"multichip_step_ms": 144.84, ...},
     "provenance": {"git_sha": ..., "jax_version": ..., "device": ...,
                    "real_device": false, "flags": {...}, ...},
     "detail": {...}}                 # source-specific raw payload

Two properties make the ledger usable as a CI gate rather than a log:

* **Direction-aware metric registry.**  Every metric name that may appear
  in ``metrics`` is declared in :data:`METRICS` with a direction
  (``higher``/``lower`` is better) and whether it is a *proxy* (chip-free,
  derived from a model) or *measured* (came from a real run).  Unknown
  metric names are schema errors — the gate can therefore always tell
  whether a delta is a regression.

* **Provenance.**  Rows record the git sha, jax/jaxlib versions, device
  kind and whether it was a real accelerator or a CPU smoke run, a
  snapshot of ``FLAGS_tpu_*`` flags and the autotune ``context_key``.  The
  staleness verdict in :func:`check` keys off ``real_device`` — a CPU
  smoke number does not refresh the "when did we last measure on silicon"
  clock, which is exactly the failure mode that let 62.x%% MFU be carried
  forward for rounds without anyone noticing.

This module is **stdlib-only** and never imports jax or the rest of
``paddle_tpu`` at module scope, so ``tools/perf_ledger.py`` can load it as
a standalone file on machines with no accelerator stack installed.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple

SCHEMA = "paddle_tpu.perf_ledger.v1"

KINDS = ("measured", "proxy", "error")


class LedgerSchemaError(ValueError):
    """A ledger row (or file) that does not conform to the v1 schema."""


@dataclass(frozen=True)
class MetricSpec:
    """Declaration of one ledger metric.

    direction: "higher" or "lower" — which way is better.
    proxy: True when the value is chip-free (model-derived), False when it
        can only come from actually running the workload.
    """

    direction: str
    unit: str
    proxy: bool
    help: str

    @property
    def higher_is_better(self) -> bool:
        return self.direction == "higher"


#: Every metric a ledger row may carry.  The gate refuses unknown names so
#: that a typo'd metric can never silently dodge regression checks.
METRICS: Dict[str, MetricSpec] = {
    # --- measured: training bench (bench.py) ---
    "mfu_percent": MetricSpec("higher", "percent_mfu", False,
                              "model FLOPs utilisation of the train step"),
    "tokens_per_sec_per_chip": MetricSpec("higher", "tokens/s/chip", False,
                                          "training throughput per chip"),
    "step_ms": MetricSpec("lower", "ms", False, "train step wall time"),
    # --- measured: multichip bench (bench.py --multichip) ---
    "multichip_step_ms": MetricSpec("lower", "ms", False,
                                    "overlap-schedule multichip step time"),
    "multichip_vs_lockstep": MetricSpec("higher", "ratio", False,
                                        "lockstep_ms / overlap_ms speedup"),
    # --- measured: serving bench (bench_serve.py) ---
    "serve_tokens_per_sec_chip": MetricSpec("higher", "tokens/s/chip", False,
                                            "serving decode throughput"),
    "serve_ttft_p95_ms": MetricSpec("lower", "ms", False,
                                    "p95 time-to-first-token"),
    "serve_latency_p95_ms": MetricSpec("lower", "ms", False,
                                       "p95 end-to-end request latency"),
    # --- proxies: chip-free, every PR gets a trajectory point ---
    "predicted_step_ms": MetricSpec("lower", "ms", True,
                                    "pod_report alpha-beta model step time"),
    "predicted_mfu": MetricSpec("higher", "percent_mfu", True,
                                "pod_report alpha-beta model MFU"),
    "plan_capacity": MetricSpec("higher", "requests", True,
                                "pod_report max concurrent requests"),
    "overlap_fraction": MetricSpec("higher", "fraction", True,
                                   "fraction of transfers overlapped"),
    "prefix_hit_rate": MetricSpec("higher", "fraction", True,
                                  "serving prefix-cache hit rate"),
    "kv_capacity_ratio_vs_bf16": MetricSpec("higher", "ratio", True,
                                            "KV capacity vs bf16 baseline"),
    "fleet_min_replicas": MetricSpec("lower", "replicas", True,
                                     "fleet_sim recommended replica count"),
    "multichip_parity": MetricSpec("higher", "bool", True,
                                   "multichip dryrun parity pass (1/0)"),
}


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------

def _dist_version(name: str) -> Optional[str]:
    try:
        from importlib import metadata as _md
        return _md.version(name)
    except Exception:
        return None


def _git_sha(repo: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo or os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0:
            return out.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        pass  # git missing / timed out — provenance degrades to null
    return None


_REAL_DEVICES = ("tpu", "gpu", "cuda", "rocm", "axon")


def is_real_device(device: Optional[str]) -> bool:
    """True when ``device`` names real silicon (not a CPU smoke run)."""
    if not device:
        return False
    d = str(device).lower()
    return any(tag in d for tag in _REAL_DEVICES)


def _flags_snapshot() -> Dict[str, Any]:
    """Snapshot FLAGS_tpu_* values *if* paddle_tpu.core.flags is loaded.

    Reads from sys.modules only — never imports, so ledger stays jax-free.
    """
    mod = sys.modules.get("paddle_tpu.core.flags")
    if mod is None:
        return {}
    reg = getattr(mod, "_REGISTRY", None)
    if not isinstance(reg, dict):
        return {}
    out = {}
    for k, v in sorted(reg.items()):
        if isinstance(v, (bool, int, float, str)) or v is None:
            out[k] = v
    return out


def _context_key() -> Optional[str]:
    mod = sys.modules.get("paddle_tpu.runtime.autotune")
    if mod is None:
        return None
    fn = getattr(mod, "context_key", None)
    if fn is None:
        return None
    try:
        return fn("bf16")
    except Exception:
        return None


def collect_provenance(device: Optional[str] = None,
                       cmd: Optional[str] = None,
                       note: Optional[str] = None,
                       repo: Optional[str] = None) -> Dict[str, Any]:
    """Build a provenance block for a freshly measured row."""
    return {
        "git_sha": _git_sha(repo),
        "jax_version": _dist_version("jax"),
        "jaxlib_version": _dist_version("jaxlib"),
        "device": device,
        "real_device": is_real_device(device),
        "flags": _flags_snapshot(),
        "context_key": _context_key(),
        "cmd": cmd,
        "note": note,
    }


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------

def new_record(source: str,
               metrics: Dict[str, float],
               *,
               kind: str = "measured",
               label: str = "",
               round: Optional[int] = None,
               ts: Optional[float] = None,
               provenance: Optional[Dict[str, Any]] = None,
               detail: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Build and validate one ledger row."""
    rec = {
        "schema": SCHEMA,
        "round": round,
        "ts": ts,
        "source": source,
        "kind": kind,
        "label": label,
        "metrics": {k: (None if v is None else float(v))
                    for k, v in metrics.items()},
        "provenance": provenance or {},
        "detail": detail or {},
    }
    validate(rec)
    return rec


def validate(rec: Any) -> Dict[str, Any]:
    """Raise :class:`LedgerSchemaError` unless ``rec`` is a valid v1 row."""
    if not isinstance(rec, dict):
        raise LedgerSchemaError(f"row is not an object: {type(rec).__name__}")
    if rec.get("schema") != SCHEMA:
        raise LedgerSchemaError(
            f"unknown schema {rec.get('schema')!r} (want {SCHEMA!r})")
    if rec.get("kind") not in KINDS:
        raise LedgerSchemaError(f"unknown kind {rec.get('kind')!r}")
    if not isinstance(rec.get("source"), str) or not rec["source"]:
        raise LedgerSchemaError("source must be a non-empty string")
    if not isinstance(rec.get("label", ""), str):
        raise LedgerSchemaError("label must be a string")
    rnd = rec.get("round")
    if rnd is not None and not isinstance(rnd, int):
        raise LedgerSchemaError(f"round must be int or null, got {rnd!r}")
    m = rec.get("metrics")
    if not isinstance(m, dict):
        raise LedgerSchemaError("metrics must be an object")
    if rec["kind"] != "error" and not m:
        raise LedgerSchemaError(f"{rec['kind']} row has no metrics")
    for name, val in m.items():
        spec = METRICS.get(name)
        if spec is None:
            raise LedgerSchemaError(f"unknown metric {name!r}")
        if val is not None and not isinstance(val, (int, float)):
            raise LedgerSchemaError(f"metric {name!r} is not numeric: {val!r}")
        if rec["kind"] == "proxy" and not spec.proxy:
            raise LedgerSchemaError(
                f"metric {name!r} is measured-only but row kind is proxy")
    prov = rec.get("provenance")
    if prov is not None and not isinstance(prov, dict):
        raise LedgerSchemaError("provenance must be an object or null")
    return rec


def dumps(rec: Dict[str, Any]) -> str:
    return json.dumps(rec, sort_keys=True, default=_json_default)


def _json_default(o: Any) -> Any:
    # numpy scalars sneak into bench dicts; coerce without importing numpy.
    for attr in ("item",):
        fn = getattr(o, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:  # tpu-lint: disable=except-pass — arbitrary .item()
                pass
    return str(o)


def append(path: str, rec: Dict[str, Any]) -> None:
    """Validate and append one row to the JSONL ledger at ``path``."""
    validate(rec)
    d = os.path.dirname(os.path.abspath(path))
    if d and not os.path.isdir(d):
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(dumps(rec) + "\n")


def load(path: str) -> List[Dict[str, Any]]:
    """Load and validate every row of a JSONL ledger."""
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise LedgerSchemaError(f"{path}:{i}: invalid JSON: {e}")
            try:
                validate(rec)
            except LedgerSchemaError as e:
                raise LedgerSchemaError(f"{path}:{i}: {e}")
            records.append(rec)
    return records


# ---------------------------------------------------------------------------
# normalizers: bench result dicts -> ledger rows
# ---------------------------------------------------------------------------

def from_bench_result(result: Dict[str, Any],
                      *,
                      round: Optional[int] = None,
                      ts: Optional[float] = None,
                      cmd: Optional[str] = None,
                      provenance: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
    """Normalize a ``bench.py`` result line (single- or multi-chip)."""
    detail = result.get("detail") or {}
    metric = result.get("metric", "")
    if result.get("error"):
        prov = dict(provenance or {})
        prov.setdefault("cmd", cmd)
        prov.setdefault("note", result["error"])
        return new_record("bench.py", {}, kind="error", round=round, ts=ts,
                          provenance=prov,
                          detail={k: v for k, v in result.items()
                                  if k != "detail"})
    if metric == "llama_train_multichip_step":
        metrics = {"multichip_step_ms": result.get("value")}
        if result.get("vs_baseline"):
            metrics["multichip_vs_lockstep"] = result["vs_baseline"]
        ov = (detail.get("overlap") or {}).get("overlap_fraction")
        if ov is not None:
            metrics["overlap_fraction"] = ov
        device = detail.get("device")
        prov = dict(provenance or collect_provenance(device=device, cmd=cmd))
        prov.setdefault("device", device)
        prov.setdefault("real_device", is_real_device(device))
        return new_record("bench.py --multichip", metrics, kind="measured",
                          round=round, ts=ts, provenance=prov, detail=detail)
    # single-chip train MFU line
    metrics = {"mfu_percent": result.get("value")}
    if detail.get("tokens_per_sec_per_chip") is not None:
        metrics["tokens_per_sec_per_chip"] = detail["tokens_per_sec_per_chip"]
    if detail.get("step_ms") is not None:
        metrics["step_ms"] = detail["step_ms"]
    device = detail.get("device")
    prov = dict(provenance or collect_provenance(device=device, cmd=cmd))
    prov.setdefault("device", device)
    prov.setdefault("real_device", is_real_device(device))
    return new_record("bench.py", metrics, kind="measured", round=round,
                      ts=ts, provenance=prov, detail=detail)


def from_bench_serve_result(result: Dict[str, Any],
                            *,
                            round: Optional[int] = None,
                            ts: Optional[float] = None,
                            cmd: Optional[str] = None,
                            provenance: Optional[Dict[str, Any]] = None
                            ) -> Dict[str, Any]:
    """Normalize a ``bench_serve.py`` result line."""
    if result.get("error"):
        prov = dict(provenance or {})
        prov.setdefault("cmd", cmd)
        prov.setdefault("note", result["error"])
        return new_record("bench_serve.py", {}, kind="error", round=round,
                          ts=ts, provenance=prov, detail=result)
    metrics = {"serve_tokens_per_sec_chip": result.get("value")}
    if result.get("ttft_p95_ms") is not None:
        metrics["serve_ttft_p95_ms"] = result["ttft_p95_ms"]
    if result.get("latency_p95_ms") is not None:
        metrics["serve_latency_p95_ms"] = result["latency_p95_ms"]
    hit = (result.get("reuse") or {}).get("prefix_hit_rate")
    if hit is not None:
        metrics["prefix_hit_rate"] = hit
    kv_dtype = (result.get("kv") or {}).get("dtype", "bf16")
    label = ":".join([str(result.get("preset", "")),
                      str(result.get("workload", "")),
                      f"kv={kv_dtype}"])
    device = result.get("device")
    prov = dict(provenance or collect_provenance(device=device, cmd=cmd))
    prov.setdefault("device", device)
    prov.setdefault("real_device", is_real_device(device))
    return new_record("bench_serve.py", metrics, kind="measured",
                      label=label, round=round, ts=ts, provenance=prov,
                      detail={k: result.get(k) for k in
                              ("fleet", "resilience", "kv", "reuse",
                               "tokens", "requests", "steps")
                              if result.get(k) is not None})


def from_pod_report(report: Dict[str, Any],
                    *,
                    round: Optional[int] = None,
                    ts: Optional[float] = None,
                    cmd: Optional[str] = None,
                    provenance: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Normalize a ``tools/pod_report.py`` verdict into a proxy row."""
    metrics: Dict[str, float] = {}
    pred = report.get("predicted") or {}
    if pred.get("step_time_ms") is not None:
        metrics["predicted_step_ms"] = pred["step_time_ms"]
    if pred.get("mfu") is not None:
        metrics["predicted_mfu"] = pred["mfu"]
    serving = report.get("serving") or {}
    if serving.get("max_concurrent_requests") is not None:
        metrics["plan_capacity"] = serving["max_concurrent_requests"]
    if serving.get("capacity_ratio_vs_bf16") is not None:
        metrics["kv_capacity_ratio_vs_bf16"] = serving[
            "capacity_ratio_vs_bf16"]
    fleet = serving.get("fleet") or {}
    if fleet.get("min_replicas") is not None:
        metrics["fleet_min_replicas"] = fleet["min_replicas"]
    if not metrics:
        raise LedgerSchemaError("pod_report payload has no proxy metrics")
    label = str(report.get("preset") or report.get("mode") or "")
    prov = dict(provenance or {"cmd": cmd, "git_sha": _git_sha()})
    return new_record("pod_report", metrics, kind="proxy", label=label,
                      round=round, ts=ts, provenance=prov,
                      detail={"mesh": report.get("mesh"),
                              "mode": report.get("mode")})


def from_fleet_report(report: Dict[str, Any],
                      *,
                      round: Optional[int] = None,
                      ts: Optional[float] = None,
                      provenance: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
    """Normalize a ``fleet_sim`` recommendation into a proxy row."""
    rec = report.get("recommended") or {}
    if rec.get("replicas") is None:
        raise LedgerSchemaError("fleet report has no recommended.replicas")
    metrics = {"fleet_min_replicas": float(rec["replicas"])}
    label = str(report.get("workload", ""))
    return new_record("fleet_sim", metrics, kind="proxy", label=label,
                      round=round, ts=ts, provenance=dict(provenance or {}),
                      detail={"recommended": rec,
                              "calibrated": report.get("calibrated")})


# ---------------------------------------------------------------------------
# artifact ingestion (driver-wrapped BENCH_r0*.json etc.)
# ---------------------------------------------------------------------------

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _round_of(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


_NOTE_ROUND_RE = re.compile(r"round\s+(\d+)")


def ingest_artifacts(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Normalize driver bench artifacts into ledger rows, deterministically.

    Handles three artifact shapes: driver-wrapped bench runs
    (``{"n", "cmd", "rc", "tail", "parsed"}``), multichip dryruns
    (``{"n_devices", "rc", "ok", ...}``) and fleet_sim reports.  Rows get
    ``ts=None`` so re-running ingestion over the same artifacts is
    byte-identical.

    Error rounds that carry a ``last_measured`` block are mined for the
    real-silicon numbers they reference: each *distinct* last_measured
    value becomes one measured row, attributed to the round named in its
    note (or the round that first reported it).
    """
    rows: List[Dict[str, Any]] = []
    seen_measured: set = set()
    for path in paths:
        with open(path) as f:
            art = json.load(f)
        rnd = _round_of(path)
        name = os.path.basename(path)
        if "n_devices" in art:  # MULTICHIP dryrun wrapper
            rows.append(new_record(
                "dryrun_multichip",
                {"multichip_parity": 1.0 if art.get("ok") else 0.0},
                kind="proxy", round=rnd,
                label=f"devices={art.get('n_devices')}",
                provenance={"cmd": f"dryrun_multichip({art.get('n_devices')})",
                            "note": name},
                detail={"rc": art.get("rc"), "ok": art.get("ok"),
                        "skipped": art.get("skipped")}))
            continue
        if "recommended" in art:  # fleet_sim report
            rows.append(from_fleet_report(
                art, round=rnd, provenance={"note": name}))
            continue
        if "rc" in art and "cmd" in art:  # driver-wrapped bench run
            parsed = art.get("parsed")
            cmd = art.get("cmd")
            n = art.get("n", rnd)
            if parsed is None:
                rows.append(new_record(
                    "bench.py", {}, kind="error", round=n,
                    provenance={"cmd": cmd, "note": f"rc={art.get('rc')}"},
                    detail={"rc": art.get("rc"), "artifact": name}))
                continue
            last = parsed.get("last_measured")
            if parsed.get("error") and last:
                # A dead round carrying a stale real-chip number: record
                # the error, and surface the referenced measurement once.
                rows.append(new_record(
                    "bench.py", {}, kind="error", round=n,
                    provenance={"cmd": cmd, "note": parsed["error"]},
                    detail={"last_measured": last, "artifact": name}))
                key = (last.get("value"), last.get("tokens_per_sec_per_chip"))
                if key not in seen_measured:
                    seen_measured.add(key)
                    note = str(last.get("note", ""))
                    m = _NOTE_ROUND_RE.search(note)
                    at_round = int(m.group(1)) if m else n
                    metrics = {"mfu_percent": last.get("value")}
                    if last.get("tokens_per_sec_per_chip") is not None:
                        metrics["tokens_per_sec_per_chip"] = last[
                            "tokens_per_sec_per_chip"]
                    rows.append(new_record(
                        "bench.py", metrics, kind="measured", round=at_round,
                        provenance={"cmd": cmd, "note": note,
                                    "device": note.split(",")[0].strip(),
                                    "real_device": is_real_device(note)},
                        detail={"carried_by": name}))
                continue
            rows.append(from_bench_result(
                parsed, round=n, cmd=cmd,
                provenance=_artifact_provenance(parsed, cmd, name)))
            continue
        raise LedgerSchemaError(f"unrecognized artifact shape: {path}")
    rows.sort(key=lambda r: (r["round"] is None, r["round"] or 0,
                             r["source"], r["label"]))
    return rows


def _artifact_provenance(parsed: Dict[str, Any], cmd: Optional[str],
                         name: str) -> Dict[str, Any]:
    device = (parsed.get("detail") or {}).get("device")
    return {"cmd": cmd, "note": name, "device": device,
            "real_device": is_real_device(device)}


# ---------------------------------------------------------------------------
# gate: regression + staleness
# ---------------------------------------------------------------------------

def _series_key(rec: Dict[str, Any], metric: str) -> Tuple[str, str, str]:
    return (metric, rec["source"], rec.get("label", ""))


def check(records: List[Dict[str, Any]],
          *,
          tol: float = 0.05,
          stale_after: int = 3,
          proxies_only: bool = False) -> Dict[str, Any]:
    """Tolerance-banded regression gate + staleness verdict.

    For every (metric, source, label) series with >= 2 points, compare the
    newest value against the previous one: a higher-is-better metric
    regresses when ``new < prev * (1 - tol)``, a lower-is-better one when
    ``new > prev * (1 + tol)``.  Improvements and in-band noise pass.

    Staleness: when the newest *measured* row from a *real device* is
    ``stale_after`` or more rounds older than the newest round in the
    ledger, the ledger is stale — the number everyone quotes no longer
    describes HEAD.  ``proxies_only=True`` restricts the gate to proxy
    metrics and skips the staleness verdict (proxies exist precisely so
    chip-free PRs still get a gated trajectory point).
    """
    series: Dict[Tuple[str, str, str], List[Tuple[int, float]]] = {}
    order = 0
    max_round = None
    newest_real_measured = None
    for rec in records:
        order += 1
        rnd = rec.get("round")
        if rnd is not None:
            max_round = rnd if max_round is None else max(max_round, rnd)
            if (rec["kind"] == "measured"
                    and (rec.get("provenance") or {}).get("real_device")):
                if newest_real_measured is None or rnd > newest_real_measured:
                    newest_real_measured = rnd
        for name, val in rec.get("metrics", {}).items():
            if val is None:
                continue
            spec = METRICS[name]
            if proxies_only and not spec.proxy:
                continue
            series.setdefault(_series_key(rec, name), []).append(
                (order, float(val)))

    regressions = []
    comparisons = 0
    for (metric, source, label), pts in sorted(series.items()):
        if len(pts) < 2:
            continue
        pts.sort(key=lambda p: p[0])
        prev, new = pts[-2][1], pts[-1][1]
        spec = METRICS[metric]
        comparisons += 1
        if spec.higher_is_better:
            bad = new < prev * (1.0 - tol)
        else:
            bad = new > prev * (1.0 + tol)
        if bad:
            regressions.append({
                "metric": metric, "source": source, "label": label,
                "previous": prev, "latest": new,
                "direction": spec.direction, "tol": tol,
                "delta_pct": round(100.0 * (new - prev) / prev, 3)
                if prev else None,
            })

    stale = None
    if not proxies_only and max_round is not None:
        if newest_real_measured is None:
            stale = {"newest_measured_round": None, "max_round": max_round,
                     "age_rounds": None,
                     "reason": "no real-device measurement in ledger"}
        else:
            age = max_round - newest_real_measured
            if age >= stale_after:
                stale = {"newest_measured_round": newest_real_measured,
                         "max_round": max_round, "age_rounds": age,
                         "reason": f"newest real-device measurement is "
                                   f"{age} rounds old (limit "
                                   f"{stale_after})"}

    ok = not regressions and stale is None
    return {"ok": ok, "regressions": regressions, "stale": stale,
            "comparisons": comparisons, "series": len(series),
            "rows": len(records), "tol": tol, "stale_after": stale_after,
            "proxies_only": proxies_only}


# ---------------------------------------------------------------------------
# report: trajectory table
# ---------------------------------------------------------------------------

def report(records: List[Dict[str, Any]], *, fmt: str = "markdown") -> str:
    """Render the per-series trajectory with deltas.

    ``fmt``: "markdown" for a table, "json" for machine consumption.
    """
    series: Dict[Tuple[str, str, str], List[Dict[str, Any]]] = {}
    for i, rec in enumerate(records):
        for name, val in rec.get("metrics", {}).items():
            if val is None:
                continue
            series.setdefault(_series_key(rec, name), []).append({
                "round": rec.get("round"), "value": float(val),
                "kind": rec["kind"], "order": i,
                "device": (rec.get("provenance") or {}).get("device"),
            })
    out = []
    for (metric, source, label), pts in sorted(series.items()):
        pts.sort(key=lambda p: p["order"])
        spec = METRICS[metric]
        first, last = pts[0]["value"], pts[-1]["value"]
        delta = None
        if first:
            delta = 100.0 * (last - first) / first
        out.append({
            "metric": metric, "source": source, "label": label,
            "direction": spec.direction, "unit": spec.unit,
            "proxy": spec.proxy, "points": len(pts),
            "trajectory": [{"round": p["round"], "value": p["value"]}
                           for p in pts],
            "latest": last, "first": first,
            "delta_pct": None if delta is None else round(delta, 3),
        })
    if fmt == "json":
        return json.dumps({"schema": SCHEMA, "rows": len(records),
                           "series": out}, indent=2, sort_keys=True)
    lines = ["| metric | source | label | dir | n | trajectory | latest | Δ% |",
             "|---|---|---|---|---|---|---|---|"]
    for s in out:
        traj = " → ".join(
            f"{p['value']:g}" + (f" (r{p['round']})" if p["round"] is not None
                                 else "")
            for p in s["trajectory"][-4:])
        arrow = "↑" if s["direction"] == "higher" else "↓"
        delta = "" if s["delta_pct"] is None else f"{s['delta_pct']:+.1f}%"
        tag = " *(proxy)*" if s["proxy"] else ""
        lines.append(f"| {s['metric']}{tag} | {s['source']} | {s['label']} "
                     f"| {arrow} | {s['points']} | {traj} "
                     f"| {s['latest']:g} {s['unit']} | {delta} |")
    return "\n".join(lines)
