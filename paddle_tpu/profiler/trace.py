"""Structured event/span tracing: the framework's flight recorder.

Counters (PR 1) say *that* time was spent; this module records *where*:
ring-buffered structured events with monotonic timestamps, rank /
replica / request tags, span nesting, and an injectable clock, gated by
``FLAGS_tpu_trace`` with the same dict-lookup-only disabled path as
``FLAGS_tpu_metrics`` — a call site pays one dict lookup + bool when
tracing is off.

Three event families share the buffer:

* **spans** — ``with span("engine/step"): ...`` records one event with
  ``t``/``dur``/``depth``/``parent`` (thread-local nesting stack);
* **request lifecycle** — ``request_event(phase, rid, ...)`` marks the
  serving transitions (queued → admitted → prefill/decode → terminal),
  from which :func:`request_timeline` / ``tools/trace_report.py``
  rebuild any request's history and a TTFT breakdown;
* **pipeline schedule** — :func:`record_pipeline_schedule` emits the
  1F1B event log of an *executed* step using the same tick arithmetic
  and event schema as ``distributed.overlap.schedule_events``, so the
  measured ``overlap_fraction`` recomputed from a sidecar is
  bit-comparable with the static simulator.

Per-process persistence is a rank-tagged JSONL **sidecar**
(:func:`write_sidecar` / :func:`read_sidecar`); :func:`merge_sidecars`
aligns ranks on shared :func:`barrier` events into one timeline, and
:func:`chrome_events` converts any event list into Chrome trace_event
dicts so structured spans land in the same Perfetto-loadable file as
the profiler's ``RecordEvent`` host spans (``Profiler.export`` merges
both streams).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..core import flags as _flags

__all__ = [
    "enabled", "event", "span", "barrier", "request_event", "events",
    "clear", "set_clock", "set_ring_capacity", "ring_capacity",
    "TraceRecorder", "record_pipeline_schedule", "pipeline_schedule_events",
    "request_timeline", "TERMINAL_PHASES", "write_sidecar", "read_sidecar",
    "merge_ranks", "merge_sidecars", "chrome_events", "sidecar_path",
    "SCHEMA", "TERMINAL_BARRIER",
]

# Same discipline as profiler.metrics: the disabled path must cost one
# dict lookup + bool, nothing else — no attribute chains, no imports.
_FLAG_DICT = _flags._REGISTRY
_FLAG_NAME = "FLAGS_tpu_trace"

SCHEMA = "paddle_tpu.trace.v1"
TERMINAL_PHASES = ("finish", "cancelled", "failed")

# Barrier every gang rank records immediately before writing its final
# sidecar — its presence in a rank's sidecar proves the rank reached
# orderly teardown (trace_report --gang checks for it per rank).
TERMINAL_BARRIER = "gang/exit"

_DEFAULT_CAPACITY = int(os.environ.get("PADDLE_TPU_TRACE_RING_CAP",
                                       "65536") or 65536)


def enabled() -> bool:
    """Is structured tracing on? (``FLAGS_tpu_trace``)"""
    return bool(_FLAG_DICT.get(_FLAG_NAME, False))


def _env_rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:
        return 0


class _NullSpan:
    """Returned by :func:`span` when tracing is disabled — one shared
    instance, so the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_rec", "_name", "_fields", "_t0", "_depth", "_parent")

    def __init__(self, rec: "TraceRecorder", name: str, fields: dict):
        self._rec = rec
        self._name = name
        self._fields = fields

    def __enter__(self):
        stack = self._rec._stack()
        self._depth = len(stack)
        self._parent = stack[-1] if stack else None
        stack.append(self._name)
        self._t0 = self._rec._clock()
        return self

    def __exit__(self, *exc):
        dur = self._rec._clock() - self._t0
        stack = self._rec._stack()
        if stack and stack[-1] == self._name:
            stack.pop()
        self._rec._append(self._name, "span", self._t0, dur=dur,
                          depth=self._depth, parent=self._parent,
                          **self._fields)
        return False


class TraceRecorder:
    """A bounded, thread-safe event ring with an injectable monotonic
    clock. The module keeps one process-wide instance; tests build their
    own with a fake clock / tiny capacity."""

    def __init__(self, capacity: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rank: Optional[int] = None):
        self._capacity = int(capacity if capacity is not None
                             else _DEFAULT_CAPACITY)
        self._clock = clock
        self._rank = _env_rank() if rank is None else int(rank)
        self._events: deque = deque(maxlen=self._capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._seq = 0
        self._dropped = 0

    # -- internals ---------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    def _append(self, name: str, kind: str, t: float, **fields) -> dict:
        ev: Dict[str, Any] = {"name": name, "kind": kind, "t": float(t),
                              "rank": self._rank}
        ev.update(fields)
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            if len(self._events) == self._capacity:
                self._dropped += 1
            self._events.append(ev)
        return ev

    # -- recording API -----------------------------------------------

    def event(self, name: str, kind: str = "instant",
              t: Optional[float] = None, **fields) -> dict:
        """Record one instant event. ``t`` overrides the clock so call
        sites that already hold a timestamp (the serving engine's
        per-step ``now``) record exactly that value."""
        return self._append(name, kind, self._clock() if t is None else t,
                            **fields)

    def span(self, name: str, **fields) -> _Span:
        """Context manager: one event with ``dur`` on exit, nested via a
        thread-local stack (``depth``/``parent``)."""
        return _Span(self, name, fields)

    def barrier(self, name: str, **fields) -> dict:
        """A cross-rank alignment point: every rank records the same
        barrier name at its local clock; :func:`merge_ranks` shifts
        clocks so these coincide."""
        return self.event(name, kind="barrier", **fields)

    # -- inspection --------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0
            self._dropped = 0

    def set_clock(self, clock: Callable[[], float]) -> None:
        self._clock = clock

    def set_rank(self, rank: int) -> None:
        self._rank = int(rank)

    def set_capacity(self, capacity: int) -> None:
        """Resize the ring, keeping the newest events."""
        capacity = int(capacity)
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        with self._lock:
            self._capacity = capacity
            self._events = deque(self._events, maxlen=capacity)

    def capacity(self) -> int:
        return self._capacity


_RECORDER = TraceRecorder()


# ---------------------------------------------------------------------------
# module-level conveniences (bound to the process recorder)
# ---------------------------------------------------------------------------

def event(name: str, kind: str = "instant", t: Optional[float] = None,
          **fields) -> Optional[dict]:
    if not _FLAG_DICT.get(_FLAG_NAME, False):
        return None
    return _RECORDER.event(name, kind=kind, t=t, **fields)


def span(name: str, **fields):
    if not _FLAG_DICT.get(_FLAG_NAME, False):
        return _NULL_SPAN
    return _RECORDER.span(name, **fields)


def barrier(name: str, **fields) -> Optional[dict]:
    if not _FLAG_DICT.get(_FLAG_NAME, False):
        return None
    return _RECORDER.barrier(name, **fields)


def request_event(phase: str, rid: str, t: Optional[float] = None,
                  **fields) -> Optional[dict]:
    """One serving-lifecycle transition for request ``rid``. ``phase``
    is queued / admitted / prefill / decode / first_token / preempted /
    replay / shed / prefix_hit / spec / recovery / quarantine /
    deadline_expired, or a terminal phase from ``TERMINAL_PHASES``."""
    if not _FLAG_DICT.get(_FLAG_NAME, False):
        return None
    return _RECORDER.event(f"serve/{phase}", kind="request", t=t,
                           rid=rid, phase=phase, **fields)


def events() -> List[dict]:
    return _RECORDER.events()


def clear() -> None:
    _RECORDER.clear()


def set_clock(clock: Callable[[], float]) -> None:
    _RECORDER.set_clock(clock)


def set_ring_capacity(capacity: int) -> None:
    _RECORDER.set_capacity(capacity)


def ring_capacity() -> int:
    return _RECORDER.capacity()


# ---------------------------------------------------------------------------
# request timelines
# ---------------------------------------------------------------------------

def request_timeline(rid: str,
                     evs: Optional[Iterable[dict]] = None) -> List[dict]:
    """All lifecycle events for one request, in record order."""
    src = _RECORDER.events() if evs is None else evs
    return [e for e in src
            if e.get("kind") == "request" and e.get("rid") == rid]


# ---------------------------------------------------------------------------
# pipeline schedule events (measured-overlap source)
# ---------------------------------------------------------------------------

def record_pipeline_schedule(pp: int, n_micro: int, *, overlap: bool,
                             step: Optional[int] = None,
                             recorder: Optional[TraceRecorder] = None
                             ) -> Optional[int]:
    """Emit the 1F1B schedule log of one *executed* pipeline step into
    the trace. The per-tick events of the real scan body are invisible
    to the host (they run inside ``lax.scan``), but the schedule is
    fully determined by (pp, n_micro, overlap) — the same arithmetic
    ``pipeline.pipeline_1f1b_value_and_grad`` compiles against — so the
    host-side log is exact, not sampled. Each schedule event is stored
    verbatim under the ``ev`` key; ``tools/trace_report.py`` recomputes
    ``transfer_stats``/``overlap_fraction`` from those dicts with the
    simulator's own serialization rule. Returns the number of schedule
    events recorded, or None when tracing is off."""
    if not _FLAG_DICT.get(_FLAG_NAME, False):
        return None
    from ..distributed.overlap import schedule_events
    evs = schedule_events(int(pp), int(n_micro), overlap=bool(overlap))
    rec = _RECORDER if recorder is None else recorder
    rec.event("pipeline/schedule", kind="pipeline_meta", pp=int(pp),
              n_micro=int(n_micro), overlap=bool(overlap), step=step,
              n_events=len(evs))
    for e in evs:
        rec.event(f"pipeline/{e['kind']}", kind="pipeline", step=step,
                  ev=dict(e))
    return len(evs)


def pipeline_schedule_events(evs: Optional[Iterable[dict]] = None,
                             step: Optional[int] = None) -> List[dict]:
    """Extract the raw schedule-event dicts back out of a trace (the
    inverse of :func:`record_pipeline_schedule`), sorted with the
    simulator's key so ordering comparisons are bit-equal."""
    src = _RECORDER.events() if evs is None else evs
    out = [dict(e["ev"]) for e in src
           if e.get("kind") == "pipeline"
           and (step is None or e.get("step") == step)]
    out.sort(key=lambda e: (e["tick"], e["stage"] if "stage" in e
                            else e["src"]))
    return out


# ---------------------------------------------------------------------------
# JSONL sidecars + multi-rank merge
# ---------------------------------------------------------------------------

def sidecar_path(base_dir: str = ".", rank: Optional[int] = None) -> str:
    """Default per-process sidecar path: ``trace_rank<N>.jsonl``."""
    r = _env_rank() if rank is None else int(rank)
    return os.path.join(base_dir, f"trace_rank{r}.jsonl")


def write_sidecar(path: str, evs: Optional[Iterable[dict]] = None,
                  rank: Optional[int] = None,
                  extra: Optional[dict] = None) -> str:
    """Write a rank-tagged JSONL sidecar: one header line (schema, rank,
    pid, wall time, drop count) then one event per line. Atomic via
    tmp-file + rename so a crash mid-dump never leaves a torn file."""
    from_recorder = evs is None
    if from_recorder:
        evs = _RECORDER.events()
    header: Dict[str, Any] = {
        "schema": SCHEMA,
        "rank": _RECORDER._rank if rank is None else int(rank),
        "pid": os.getpid(),
        "wall_time": time.time(),
        "dropped": _RECORDER.dropped() if from_recorder else 0,
    }
    if extra:
        header.update(extra)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(header, sort_keys=True) + "\n")
        for e in evs:
            f.write(json.dumps(e, sort_keys=True, default=str) + "\n")
    os.replace(tmp, path)
    return path


def read_sidecar(path: str) -> Tuple[dict, List[dict]]:
    """Load ``(header, events)`` from a sidecar written by
    :func:`write_sidecar`. Raises ValueError on a torn/corrupt file."""
    with open(path) as f:
        lines = [ln for ln in (raw.strip() for raw in f) if ln]
    if not lines:
        raise ValueError(f"{path}: empty trace sidecar")
    try:
        header = json.loads(lines[0])
        evs = [json.loads(ln) for ln in lines[1:]]
    except json.JSONDecodeError as exc:
        raise ValueError(f"{path}: corrupt trace sidecar: {exc}") from exc
    if not isinstance(header, dict) or header.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a {SCHEMA} sidecar "
                         f"(header={header!r})")
    return header, evs


def merge_ranks(per_rank: Dict[int, List[dict]]) -> List[dict]:
    """Merge per-rank event lists into one timeline. Ranks run on
    unsynchronised monotonic clocks; alignment uses the first barrier
    event (``kind == "barrier"``) whose name every rank recorded — each
    rank's clock is shifted so that barrier lands at the reference
    (lowest) rank's timestamp. Without a shared barrier, clocks are
    taken as-is. Events gain the owning ``rank`` tag and sort by
    ``(t, rank, seq)``."""
    if not per_rank:
        return []
    ranks = sorted(per_rank)
    ref = ranks[0]
    barriers: Dict[int, Dict[str, float]] = {}
    for r in ranks:
        names: Dict[str, float] = {}
        for e in per_rank[r]:
            if e.get("kind") == "barrier" and e["name"] not in names:
                names[e["name"]] = e["t"]
        barriers[r] = names
    shared = None
    for e in per_rank[ref]:
        if e.get("kind") == "barrier" and all(
                e["name"] in barriers[r] for r in ranks):
            shared = e["name"]
            break
    merged: List[dict] = []
    for r in ranks:
        offset = 0.0
        if shared is not None:
            offset = barriers[ref][shared] - barriers[r][shared]
        for e in per_rank[r]:
            out = dict(e)
            out["t"] = e["t"] + offset
            out["rank"] = r
            merged.append(out)
    merged.sort(key=lambda e: (e["t"], e["rank"], e.get("seq", 0)))
    return merged


def merge_sidecars(paths: Iterable[str]) -> List[dict]:
    """Read several rank sidecars and :func:`merge_ranks` them."""
    per_rank: Dict[int, List[dict]] = {}
    for p in paths:
        header, evs = read_sidecar(p)
        per_rank.setdefault(int(header.get("rank", 0)), []).extend(evs)
    return merge_ranks(per_rank)


# ---------------------------------------------------------------------------
# Chrome trace_event conversion (Perfetto-loadable, merged with the
# profiler's RecordEvent host spans by Profiler.export)
# ---------------------------------------------------------------------------

def chrome_events(evs: Optional[Iterable[dict]] = None) -> List[dict]:
    """Convert structured events to Chrome trace_event dicts: spans
    become "X" complete events, everything else an "i" instant. ``pid``
    is the rank (so merged multi-rank traces get one track group per
    rank) and extra fields ride in ``args``."""
    src = _RECORDER.events() if evs is None else evs
    out: List[dict] = []
    for e in src:
        rank = int(e.get("rank", 0))
        args = {k: v for k, v in e.items()
                if k not in ("name", "kind", "t", "dur", "rank", "seq")}
        ch: Dict[str, Any] = {"name": e["name"], "cat": e.get("kind", ""),
                              "ts": e["t"] * 1e6, "pid": rank,
                              "tid": int(e.get("depth", 0))}
        if "dur" in e:
            ch["ph"] = "X"
            ch["dur"] = e["dur"] * 1e6
        else:
            ch["ph"] = "i"
            ch["s"] = "t"
        if args:
            ch["args"] = args
        out.append(ch)
    return out
