"""Profiler.

Reference analog: python/paddle/profiler/profiler.py:344 (Profiler with
make_scheduler state machine, chrome-trace export) over the C++ HostTracer/
CudaTracer (paddle/fluid/platform/profiler/). TPU-native: jax.profiler
(xprof) captures device traces; RecordEvent instruments host spans into the
same trace via jax.profiler.TraceAnnotation AND into a self-contained
host-span buffer that `export_chrome_tracing` serializes as Chrome
`trace_event` JSON — so traces work on CPU CI with no xprof attached.

Telemetry siblings in this package:
  metrics.py          — Counter/Gauge/Histogram registry (FLAGS_tpu_metrics)
  compile_tracker.py  — jax.monitoring compile/retrace accounting
  xmem.py             — per-executable memory/cost analysis capture
  numerics.py         — NaN/Inf watchdog + first-bad-op localization
                        (FLAGS_tpu_check_nan_inf)
  trace.py            — structured event/span flight recorder with
                        JSONL sidecars (FLAGS_tpu_trace)
  exporter.py         — live HTTP observability endpoint: /metrics,
                        /healthz, /slo, /incidents, /trace/tail
                        (FLAGS_tpu_metrics_port)
  ledger.py           — provenance-stamped perf ledger: schema, direction-
                        aware metric registry, regression/staleness gate
                        (stdlib-only; CLI at tools/perf_ledger.py)
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Optional

import jax

from . import metrics
from . import compile_tracker
from . import xmem
from . import numerics
from . import trace
from . import exporter
from . import ledger

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "make_scheduler",
           "RecordEvent", "export_chrome_tracing", "benchmark", "metrics",
           "compile_tracker", "xmem", "numerics", "trace", "exporter",
           "ledger"]

# host-span aggregation for the summary stats table (reference:
# profiler/profiler_statistic.py — EventSummary/statistic_data tables).
# RecordEvent feeds every ACTIVE profiler's own stats dict, so
# concurrent Profiler instances don't clobber each other.
_ACTIVE_PROFILERS: list = []

# jax.monitoring listeners live for the whole process; install once here
# so compiles are counted even before the first Profiler is constructed.
compile_tracker.install()


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """reference: profiler.py:117 — step-indexed state machine."""
    if closed < 0 or ready < 0 or skip_first < 0:
        raise ValueError(
            f"make_scheduler: closed/ready/skip_first must be >= 0, got "
            f"closed={closed}, ready={ready}, skip_first={skip_first}")
    if record < 1:
        raise ValueError(
            f"make_scheduler: record must be >= 1 (a period that never "
            f"records profiles nothing), got record={record}")
    if repeat < 0:
        raise ValueError(
            f"make_scheduler: repeat must be >= 0 (0 = repeat forever), "
            f"got repeat={repeat}")
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready handler that writes the profiler's host-span buffer
    as a Chrome trace_event JSON file under `dir_name` (reference:
    profiler.py export_chrome_tracing). Self-contained: works with no
    xprof/TPU attached — chrome://tracing and Perfetto load the file."""

    def handler(prof):
        prof._log_dir = dir_name
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.pt.trace.json")
        prof.export(path)
    return handler


class RecordEvent:
    """Host-span annotation visible in the xprof trace
    (reference: paddle/fluid/platform/profiler/event_tracing.h) and
    buffered into every RECORD-state profiler for chrome-trace export."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None
        self._t0 = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        self._t0 = time.perf_counter()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._t0 is not None and _ACTIVE_PROFILERS:
            t1 = time.perf_counter()
            dt = t1 - self._t0
            event = None
            for p in _ACTIVE_PROFILERS:
                stats = p._span_stats
                calls, total, mx = stats.get(self.name, (0, 0.0, 0.0))
                stats[self.name] = (calls + 1, total + dt, max(mx, dt))
                if p._state in (ProfilerState.RECORD,
                                ProfilerState.RECORD_AND_RETURN) \
                        and len(p._trace_events) < p._trace_buffer_cap:
                    if event is None:
                        # complete ("X") event: one dict carries the
                        # begin/end pair; ts/dur are microseconds
                        event = {"name": self.name, "ph": "X",
                                 "cat": "host",
                                 "ts": self._t0 * 1e6, "dur": dt * 1e6,
                                 "pid": os.getpid(),
                                 "tid": threading.get_ident()}
                    p._trace_events.append(event)
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def _record_span(name: str):
    """RecordEvent when any profiler is live, else a no-op context —
    the zero-cost guard hot paths (optimizer/collectives/io/inference)
    use so an un-profiled step pays one list truthiness check."""
    if _ACTIVE_PROFILERS:
        return RecordEvent(name)
    return contextlib.nullcontext()


def _metadata_events(events: list) -> list:
    """Chrome "M" metadata events naming every pid/tid seen in
    `events`: the host process keeps its real pid ("host <pid>"),
    structured-trace events use the rank as pid ("rank <N>"), so a
    merged multi-rank trace groups into legible Perfetto tracks."""
    host_pid = os.getpid()
    pids = []
    tids = []
    for e in events:
        pid, tid = e.get("pid"), e.get("tid")
        if pid is not None and pid not in pids:
            pids.append(pid)
        if pid is not None and tid is not None and (pid, tid) not in tids:
            tids.append((pid, tid))
    meta = []
    for pid in pids:
        name = f"host {pid}" if pid == host_pid else f"rank {pid}"
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": name}})
    for pid, tid in tids:
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": f"thread {tid}"}})
    return meta


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0,
                                             record=hi - lo, repeat=1)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._log_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR",
                                       "/tmp/paddle_tpu_profile")
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._active = False
        self._step_times = []
        self._span_stats: dict = {}
        self._trace_events: list = []
        self._trace_buffer_cap = int(os.environ.get(
            "PADDLE_TPU_TRACE_BUFFER_CAP", "1000000"))
        self._last = None

    def start(self):
        self._state = self._scheduler(self._step) if self._scheduler \
            else ProfilerState.RECORD
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN) \
                and not self._timer_only:
            jax.profiler.start_trace(self._log_dir)
            self._active = True
        self._span_stats.clear()
        self._trace_events.clear()
        if self not in _ACTIVE_PROFILERS:
            _ACTIVE_PROFILERS.append(self)
        self._last = time.perf_counter()

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        if self in _ACTIVE_PROFILERS:
            _ACTIVE_PROFILERS.remove(self)
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1
        if self._scheduler is None:
            return
        new_state = self._scheduler(self._step)
        if new_state != self._state:
            recording = self._state in (ProfilerState.RECORD,
                                        ProfilerState.RECORD_AND_RETURN)
            will_record = new_state in (ProfilerState.RECORD,
                                        ProfilerState.RECORD_AND_RETURN)
            if will_record and not self._active and not self._timer_only:
                jax.profiler.start_trace(self._log_dir)
                self._active = True
            if recording and not will_record and self._active:
                jax.profiler.stop_trace()
                self._active = False
            self._state = new_state

    def export(self, path: Optional[str] = None):
        """Write the buffered host spans as a Chrome trace_event file
        (the `{"traceEvents": [...]}` object form). Structured events
        from `profiler.trace` (when FLAGS_tpu_trace is on) are merged
        into the same file, and process_name/thread_name metadata
        events label every pid/tid so multi-rank merged traces read as
        named tracks in Perfetto. Returns the path."""
        if path is None:
            path = os.path.join(self._log_dir,
                                f"host_{os.getpid()}.pt.trace.json")
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        events = list(self._trace_events)
        if trace.enabled():
            events.extend(trace.chrome_events())
        payload = {
            "traceEvents": _metadata_events(events) + events,
            "displayTimeUnit": "ms",
            "metadata": {"producer": "paddle_tpu.profiler",
                         "steps": self._step},
        }
        with open(path, "w") as f:
            json.dump(payload, f)
        return path

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        units = {"s": 1.0, "ms": 1e3, "us": 1e6, "ns": 1e9}
        u = unit if unit in units else "ms"
        scale = units[u]
        arr = np.asarray(self._step_times[-100:])
        return (f"avg step: {arr.mean() * scale:.2f} {u}, "
                f"ips: {1.0 / max(arr.mean(), 1e-9):.2f} steps/s")

    def _compilation_section(self) -> list:
        """The "Compilation" block of summary_table: backend compiles,
        cumulative compile seconds, per-function retrace attribution."""
        st = compile_tracker.stats()
        lines = ["Compilation",
                 f"  backend compiles: {st['compile_count']}  "
                 f"(cumulative {st['compile_seconds']:.3f} s)",
                 f"  jaxpr traces: {st['trace_count']}  "
                 f"(cumulative {st['trace_seconds']:.3f} s)"]
        if st["persistent_cache_hits"] or st["persistent_cache_misses"]:
            lines.append(
                f"  persistent cache: {st['persistent_cache_hits']} hits / "
                f"{st['persistent_cache_misses']} misses")
        fns = st["functions"]
        if fns:
            lines.append(f"  traced functions: {len(fns)}, "
                         f"retraces: {st['retraces']}")
            worst = sorted(fns.items(), key=lambda kv: -kv[1]["traces"])[:5]
            for name, e in worst:
                mark = "  <-- RETRACING" if e["retraces"] else ""
                lines.append(f"    {name[:48]:<48} {e['traces']:>4} traces "
                             f"({e['retraces']} retraces){mark}")
        return lines

    def summary_table(self, sorted_by="total", time_unit="ms") -> str:
        """Host-span stats table (reference:
        profiler_statistic.py _build_table): name / calls / total / avg /
        max / % of wall, plus the Compilation section."""
        units = {"s": 1.0, "ms": 1e3, "us": 1e6, "ns": 1e9}
        unit = units.get(time_unit, 1e3)
        if time_unit not in units:
            time_unit = "ms"
        wall = sum(self._step_times) or sum(
            t for _, t, _ in self._span_stats.values()) or 1e-12
        rows = [(name, c, tot, tot / c, mx)
                for name, (c, tot, mx) in self._span_stats.items()]
        key = {"total": 2, "calls": 1, "avg": 3, "max": 4}.get(sorted_by, 2)
        rows.sort(key=lambda r: -r[key])
        header = (f"{'Name':<32}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                  f"{'Avg(' + time_unit + ')':>12}"
                  f"{'Max(' + time_unit + ')':>12}{'Ratio%':>8}")
        lines = ["-" * len(header), header, "-" * len(header)]
        for name, c, tot, avg, mx in rows:
            lines.append(
                f"{name[:32]:<32}{c:>8}{tot * unit:>14.3f}"
                f"{avg * unit:>12.3f}{mx * unit:>12.3f}"
                f"{100.0 * tot / wall:>8.1f}")
        lines.append("-" * len(header))
        lines.extend(self._compilation_section())
        lines.append("-" * len(header))
        lines.extend(xmem.summary_lines())
        lines.append("-" * len(header))
        lines.extend(numerics.summary_lines())
        lines.append("-" * len(header))
        from ..ops import autotune as _autotune
        lines.extend(_autotune.summary_lines())
        lines.append("-" * len(header))
        from ..analysis import core as _lint_core
        lines.extend(_lint_core.summary_lines())
        lines.append("-" * len(header))
        from ..distributed import fault_tolerance as _ft
        lines.extend(_ft.summary_lines())
        lines.append("-" * len(header))
        from .. import runtime as _runtime
        lines.extend(_runtime.summary_lines())
        lines.append("-" * len(header))
        from ..serving import engine as _serving
        lines.extend(_serving.summary_lines())
        lines.append("-" * len(header))
        from ..serving import autoscale as _autoscale
        lines.extend(_autoscale.fleet_summary_lines())
        lines.append("-" * len(header))
        if self._step_times:
            lines.append(self.step_info(time_unit))
        return "\n".join(lines)

    def summary(self, sorted_by="total", op_detail=True, thread_sep=False,
                time_unit="ms"):
        print(self.summary_table(sorted_by=sorted_by if isinstance(
            sorted_by, str) else "total", time_unit=time_unit), flush=True)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class benchmark:
    """reference: profiler/timer.py (Benchmark.step_info — reader-cost +
    ips over a moving window)."""

    def __init__(self):
        self._times = []
        self._samples = []
        self._last = None

    def begin(self):
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
            self._samples.append(num_samples or 1)
        self._last = now

    def end(self):
        pass

    def report(self):
        import numpy as np
        arr = np.asarray(self._times or [0.0])
        n = float(np.sum(self._samples)) if self._samples else 0.0
        total = float(np.sum(arr)) or 1e-12
        return {"avg_s": float(arr.mean()), "steps": len(self._times),
                "p50_s": float(np.percentile(arr, 50)),
                "p95_s": float(np.percentile(arr, 95)),
                "max_s": float(arr.max()),
                "ips": n / total,
                "steps_per_sec": len(self._times) / total}
