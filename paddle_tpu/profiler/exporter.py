"""Live observability endpoint: /metrics, /healthz, /slo, /incidents, /trace.

A stdlib ``http.server`` served from a daemon thread, gated by
``FLAGS_tpu_metrics_port``:

* ``0`` (default): disabled.  The check in :func:`maybe_serve` is one
  dict lookup + bool — zero per-step cost when observability is off.
* ``-1``: bind an ephemeral port (tests, multi-process benches).
* ``>0``: bind that port; if it is already taken (two replicas on one
  host), fall back to an ephemeral port instead of crashing the replica.

Routes:

* ``/metrics`` — the PR-1 metric registry in Prometheus text exposition
  format (``profiler.metrics.to_prometheus``), Grafana-scrapeable as-is.
* ``/healthz`` — liveness: uptime, pid, watchdog incident count, per-role
  attachment state (engine running/queue depth, router replica states,
  train-loop step progress).
* ``/slo`` — every attached engine's ``slo_report()`` plus, when a router
  with an autoscaler is attached, the ``SLOBurnGauge`` burn-rate windows,
  the last autoscale recommendation and ``fleet_stats()``.
* ``/incidents?n=`` — tail of the watchdog incident buffer.
* ``/trace/tail?n=`` — tail of the flight-recorder trace ring.

``Router``, ``LLMEngine`` and ``run_train_loop`` call
:func:`maybe_serve` at construction; one process-wide exporter serves all
attached objects.  Scrapes read through the registry's own lock and touch
only snapshot-style accessors — they never block ``step()``.

Module-scope imports here are restricted to stdlib + ``core.flags`` +
``profiler.metrics`` so the serving stack stays loadable without jax
(``tools/fleet_sim.py`` imports ``serving.router`` standalone).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from ..core import flags as _flags
from . import metrics as _metrics

__all__ = ["MetricsExporter", "maybe_serve", "serve", "active", "shutdown"]

# same convention as profiler.metrics: the disabled path must cost one
# dict lookup + bool check, never a call chain through get_flags
_FLAG_DICT = _flags._REGISTRY
_FLAG_NAME = "FLAGS_tpu_metrics_port"

_PORTFILE_ENV = "PADDLE_TPU_METRICS_PORTFILE"

_LOCK = threading.Lock()
_EXPORTER: Optional["MetricsExporter"] = None


def _json_default(o: Any) -> Any:
    item = getattr(o, "item", None)  # numpy scalars without importing numpy
    if callable(item):
        try:
            return item()
        except Exception:  # tpu-lint: disable=except-pass — arbitrary .item()
            pass
    return str(o)


class MetricsExporter:
    """One HTTP endpoint serving every attached engine/router/train loop."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self.requested_port = port
        self.host = host
        self.port: Optional[int] = None  # bound port, set by start()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None
        self._attach_lock = threading.Lock()
        self._engines: List[Any] = []
        self._router: Any = None
        self._train_status: Any = None  # zero-arg callable -> dict

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "MetricsExporter":
        if self._httpd is not None:
            return self
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                exporter._handle(self)

            def log_message(self, *args):  # silence per-request stderr
                pass

        port = 0 if self.requested_port < 0 else self.requested_port
        try:
            httpd = ThreadingHTTPServer((self.host, port), Handler)
        except OSError:
            # port taken (another replica on this host): fall back to an
            # ephemeral port rather than killing the process
            httpd = ThreadingHTTPServer((self.host, 0), Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._started_at = time.monotonic()
        portfile = os.environ.get(_PORTFILE_ENV)
        if portfile:
            with open(portfile, "w") as f:
                f.write(str(self.port))
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="paddle-tpu-metrics-exporter",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- attachment -------------------------------------------------------

    def attach(self, role: Optional[str], obj: Any) -> None:
        if role is None or obj is None:
            return
        with self._attach_lock:
            if role == "engine":
                if not any(e is obj for e in self._engines):
                    self._engines.append(obj)
            elif role == "router":
                self._router = obj
            elif role == "train":
                self._train_status = obj  # callable returning a dict

    # -- request handling -------------------------------------------------

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        url = urlparse(req.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/metrics":
                body = _metrics.to_prometheus()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif url.path == "/healthz":
                body = json.dumps(self._healthz(), indent=2, sort_keys=True,
                                  default=_json_default)
                ctype = "application/json"
            elif url.path == "/slo":
                body = json.dumps(self._slo(), indent=2, sort_keys=True,
                                  default=_json_default)
                ctype = "application/json"
            elif url.path == "/incidents":
                n = int(q.get("n", ["50"])[0])
                body = json.dumps(self._incidents(n), indent=2,
                                  sort_keys=True, default=_json_default)
                ctype = "application/json"
            elif url.path == "/trace/tail":
                n = int(q.get("n", ["100"])[0])
                body = json.dumps(self._trace_tail(n), indent=2,
                                  sort_keys=True, default=_json_default)
                ctype = "application/json"
            else:
                req.send_response(404)
                req.send_header("Content-Type", "text/plain")
                req.end_headers()
                req.wfile.write(b"not found\n")
                return
        except Exception as e:  # a broken scrape must never kill serving
            req.send_response(500)
            req.send_header("Content-Type", "text/plain")
            req.end_headers()
            req.wfile.write(f"scrape error: {e}\n".encode())
            return
        data = body.encode()
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    # -- views ------------------------------------------------------------

    def _healthz(self) -> Dict[str, Any]:
        from ..runtime import watchdog as _watchdog  # jax-free, lazy
        incidents = _watchdog.incidents()
        out: Dict[str, Any] = {
            "ok": True,
            "pid": os.getpid(),
            "port": self.port,
            "uptime_s": (time.monotonic() - self._started_at
                         if self._started_at is not None else None),
            "metrics_enabled": _metrics.enabled(),
            "watchdog": {
                "incident_count": len(incidents),
                "last_incident": _watchdog.last_incident(),
            },
        }
        with self._attach_lock:
            engines = list(self._engines)
            router = self._router
            train = self._train_status
        out["engines"] = [self._engine_health(e) for e in engines]
        if router is not None:
            try:
                out["router"] = {"replicas": router.replica_states()}
            except Exception as e:
                out["router"] = {"error": str(e)}
        if train is not None:
            try:
                out["train"] = dict(train())
            except Exception as e:
                out["train"] = {"error": str(e)}
        return out

    @staticmethod
    def _engine_health(eng: Any) -> Dict[str, Any]:
        h: Dict[str, Any] = {}
        sched = getattr(eng, "scheduler", None)
        for attr in ("num_running", "num_waiting"):
            try:
                v = getattr(sched, attr, None)
                h[attr] = v() if callable(v) else v
            except Exception:
                h[attr] = None
        return h

    def _slo(self) -> Dict[str, Any]:
        with self._attach_lock:
            engines = list(self._engines)
            router = self._router
        out: Dict[str, Any] = {
            "engines": [], "router": None, "burn_rates": None,
            "fleet": None,
        }
        for eng in engines:
            try:
                out["engines"].append(eng.slo_report())
            except Exception as e:
                out["engines"].append({"error": str(e)})
        if router is not None:
            r: Dict[str, Any] = {"live_replicas": None,
                                 "last_recommendation": None}
            try:
                r["live_replicas"] = router.live_replicas()
            except Exception:  # tpu-lint: disable=except-pass — best-effort probe
                pass
            auto = getattr(router, "autoscaler", None)
            last = getattr(router, "last_recommendation", None)
            if last is not None:
                to_dict = getattr(last, "to_dict", None)
                r["last_recommendation"] = (to_dict() if callable(to_dict)
                                            else last)
            if auto is not None:
                gauge = getattr(auto, "gauge", None)
                clock = getattr(auto, "_clock", time.monotonic)
                if gauge is not None:
                    try:
                        out["burn_rates"] = gauge.burn_rates(clock())
                    except Exception as e:
                        out["burn_rates"] = {"error": str(e)}
                fleet_stats = getattr(auto, "fleet_stats", None)
                if callable(fleet_stats):
                    try:
                        out["fleet"] = fleet_stats()
                    except Exception as e:
                        out["fleet"] = {"error": str(e)}
            out["router"] = r
        return out

    def _incidents(self, n: int) -> Dict[str, Any]:
        from ..runtime import watchdog as _watchdog  # jax-free, lazy
        incidents = _watchdog.incidents()
        return {"count": len(incidents), "tail": incidents[-max(n, 0):]}

    def _trace_tail(self, n: int) -> Dict[str, Any]:
        from . import trace as _trace  # jax-free, lazy
        events = _trace.events()
        return {"enabled": _trace.enabled(), "count": len(events),
                "tail": events[-max(n, 0):]}


# ---------------------------------------------------------------------------
# module-level singleton
# ---------------------------------------------------------------------------

def active() -> Optional[MetricsExporter]:
    """The running process-wide exporter, or None."""
    return _EXPORTER


def serve(port: Optional[int] = None, host: str = "127.0.0.1",
          role: Optional[str] = None, obj: Any = None) -> MetricsExporter:
    """Start (or reuse) the process-wide exporter and optionally attach."""
    global _EXPORTER
    with _LOCK:
        if _EXPORTER is None:
            if port is None:
                port = int(_FLAG_DICT.get(_FLAG_NAME, 0) or 0)
            _EXPORTER = MetricsExporter(port, host=host).start()
        exp = _EXPORTER
    exp.attach(role, obj)
    return exp


def maybe_serve(role: Optional[str] = None,
                obj: Any = None) -> Optional[MetricsExporter]:
    """Start/attach the exporter iff FLAGS_tpu_metrics_port is set.

    The disabled path is one dict lookup + bool check — safe to call from
    every Engine/Router constructor and train-loop entry.
    """
    if not _FLAG_DICT.get(_FLAG_NAME, 0):
        return None
    return serve(role=role, obj=obj)


def shutdown() -> None:
    """Stop the process-wide exporter (tests)."""
    global _EXPORTER
    with _LOCK:
        exp, _EXPORTER = _EXPORTER, None
    if exp is not None:
        exp.stop()
