"""Executable-level memory & cost observability (xmem).

Every place the framework lowers a function to an XLA executable — the
`to_static` jit cache (jit/api.py), the static-graph Executor
(static/program.py), the inference Predictor, and bench.py — reports the
compiled executable's `memory_analysis()` (argument / output / temp /
generated-code bytes, and the derived per-device peak) and
`cost_analysis()` (flops, bytes accessed) into one process-global store.

Why this exists: host-side telemetry (metrics.py / compile_tracker.py)
says *when* and *how long* XLA compiled, but capacity planning needs
*what the executable costs in HBM and FLOPs* — the number that decides
whether a config can run at all. XLA computes it for every executable;
this module stops throwing it away.

Gating: capture costs one extra-cheap branch when off. When on (the
``FLAGS_tpu_xmem`` flag, or implicitly whenever ``FLAGS_tpu_metrics``
is on), the jit entry points switch to AOT compilation
(``fn.lower(...).compile()``) for NEW signatures so the analysis comes
from the same single compile that serves the call — capture never
double-compiles.

Surfaces:
  * ``stats()`` / ``profiles()``    — snapshot of captured executables
  * ``Profiler.summary_table()``    — renders the "Memory" section
  * ``paddle_tpu.device.memory_stats`` — merges the static peaks with
    the live PJRT allocator counters
  * ``tools/pod_report.py``         — pod-fit report on a virtual mesh
  * metrics registry                — ``xmem_peak_bytes{fn=}`` etc.
    whenever ``FLAGS_tpu_metrics`` is on
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..core import flags as _flags
from . import metrics as _metrics

__all__ = ["enabled", "enable", "disable", "capture_compiled", "analyze",
           "aot_compile", "profiles", "stats", "reset", "max_static_peak",
           "total_generated_code", "summary_lines", "peak_bytes_of",
           "record_kernel_estimate", "kernel_estimates",
           "record_reservation", "reservations"]

_FLAG_DICT = _flags._REGISTRY
_FLAG_NAME = "FLAGS_tpu_xmem"

_lock = threading.Lock()
# (source, name, sig) -> profile dict; LRU-bounded so a shape-polymorphic
# serving loop cannot grow the store without bound
_STORE: "OrderedDict[tuple, Dict[str, Any]]" = OrderedDict()
_STORE_CAP = int(os.environ.get("PADDLE_TPU_XMEM_CAP", "256"))


def enabled() -> bool:
    """Capture is on when FLAGS_tpu_xmem is set, or implicitly whenever
    the metrics registry is on (the numbers must reach the exporter)."""
    return bool(_FLAG_DICT.get(_FLAG_NAME, False)) or _metrics.enabled()


def enable():
    _flags.set_flags({_FLAG_NAME: True})


def disable():
    _flags.set_flags({_FLAG_NAME: False})


def _cost_dict(compiled) -> Dict[str, float]:
    """Normalize compiled.cost_analysis() across jax versions: it has
    returned a bare dict, a list of per-computation dicts, and None."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if isinstance(ca, dict) else {}


def peak_bytes_of(mem) -> int:
    """Per-device peak HBM of one executable from CompiledMemoryStats:
    arguments + outputs + scratch + code, minus buffers aliased
    (donated) between argument and output — the set XLA reserves while
    the executable runs."""
    return int(mem.argument_size_in_bytes + mem.output_size_in_bytes
               + mem.temp_size_in_bytes + mem.generated_code_size_in_bytes
               - mem.alias_size_in_bytes)


def capture_compiled(source: str, name: str, compiled,
                     sig: Any = None) -> Optional[Dict[str, Any]]:
    """Record one compiled executable's memory/cost analysis.

    `compiled` is a jax.stages.Compiled (or anything exposing
    memory_analysis()/cost_analysis()). Returns the stored profile, or
    None when the backend provides no analysis. Never raises: the
    observability layer must not cost the computation."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is None:
        return None
    cost = _cost_dict(compiled)
    profile = {
        "source": source,
        "name": name,
        "sig": repr(sig) if sig is not None else "",
        "argument_bytes": int(mem.argument_size_in_bytes),
        "output_bytes": int(mem.output_size_in_bytes),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "alias_bytes": int(mem.alias_size_in_bytes),
        "generated_code_bytes": int(mem.generated_code_size_in_bytes),
        "peak_bytes": peak_bytes_of(mem),
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
    }
    key = (source, name, profile["sig"])
    with _lock:
        _STORE[key] = profile
        _STORE.move_to_end(key)
        while len(_STORE) > _STORE_CAP:
            _STORE.popitem(last=False)
    if _metrics.enabled():
        label = name if not profile["sig"] else f"{name}|{profile['sig']}"
        label = label[:120]
        _metrics.gauge("xmem_peak_bytes",
                       "Per-device static peak HBM of the executable",
                       fn=label).set(profile["peak_bytes"])
        _metrics.gauge("xmem_temp_bytes",
                       "Scratch (temp) bytes of the executable",
                       fn=label).set(profile["temp_bytes"])
        _metrics.gauge("xmem_flops",
                       "Per-device FLOPs of one executable invocation",
                       fn=label).set(profile["flops"])
        _metrics.counter("xmem_captures_total",
                         "Executables captured by the xmem layer").inc()
    return profile


def aot_compile(source: str, name: str, jit_fn, args, kwargs=None,
                sig: Any = None):
    """Lower+compile `jit_fn` ahead of time, capture its analysis, and
    return the Compiled (callable with the same concrete arguments).
    Returns None on any failure — callers fall back to the traced path.

    This is THE way capture avoids double compiles: the jit entry
    points call this INSTEAD of letting the first traced call compile
    internally, then dispatch every same-signature call through the
    returned executable."""
    # every framework compile funnels through here — activating the
    # persistent XLA cache at this chokepoint gives tests/examples/
    # tools warm starts when FLAGS_tpu_persistent_cache is on
    # (ensure() is internally best-effort: off-or-failed is a no-op)
    from paddle_tpu.core import compile_cache
    compile_cache.ensure()
    try:
        lowered = jit_fn.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
    except Exception:
        return None
    capture_compiled(source, name, compiled, sig=sig)
    return compiled


def analyze(jit_fn, *abstract_args, source: str = "manual",
            name: Optional[str] = None, **abstract_kwargs):
    """One-shot AOT analysis of a jitted function against (possibly
    abstract jax.ShapeDtypeStruct) arguments: compiles, captures, and
    returns (profile, compiled). Raises on compile failure — the
    explicit-analysis path (pod_report) wants the real error."""
    from paddle_tpu.core import compile_cache
    compile_cache.ensure()
    lowered = jit_fn.lower(*abstract_args, **abstract_kwargs)
    compiled = lowered.compile()
    profile = capture_compiled(
        source, name or getattr(jit_fn, "__name__", "fn"), compiled)
    return profile, compiled


def profiles() -> List[Dict[str, Any]]:
    with _lock:
        return [dict(p) for p in _STORE.values()]


def stats() -> Dict[str, Any]:
    """Aggregate snapshot: executable count, max/total static peaks."""
    with _lock:
        vals = list(_STORE.values())
    return {
        "executables": len(vals),
        "max_peak_bytes": max((p["peak_bytes"] for p in vals), default=0),
        "total_temp_bytes": sum(p["temp_bytes"] for p in vals),
        "total_generated_code_bytes": sum(p["generated_code_bytes"]
                                          for p in vals),
        "profiles": [dict(p) for p in vals],
    }


def max_static_peak() -> int:
    """Largest per-device peak across captured executables — the
    analysis-derived lower bound on HBM high-water (any one of these
    executables running alone needs this much)."""
    with _lock:
        return max((p["peak_bytes"] for p in _STORE.values()), default=0)


def total_generated_code() -> int:
    with _lock:
        return sum(p["generated_code_bytes"] for p in _STORE.values())


# ---------------------------------------------------------------------------
# Pallas kernel VMEM estimates (fed by analysis/kernel_checks — the
# Level-3 verifier computes blocks+scratch per pallas_call site; this
# store makes the numbers visible to the Profiler and pod_report)
# ---------------------------------------------------------------------------

_KERNELS: "OrderedDict[tuple, Dict[str, Any]]" = OrderedDict()
_KERNELS_CAP = 256


def record_kernel_estimate(kernel: str, vmem_bytes: int, **detail) -> None:
    """Record one kernel's estimated per-invocation VMEM footprint.
    Keyed by (kernel, call site) so retracing the same site updates in
    place; LRU-bounded like the executable store."""
    entry = {"kernel": kernel, "vmem_bytes": int(vmem_bytes)}
    entry.update(detail)
    key = (kernel, entry.get("where", ""))
    with _lock:
        _KERNELS[key] = entry
        _KERNELS.move_to_end(key)
        while len(_KERNELS) > _KERNELS_CAP:
            _KERNELS.popitem(last=False)
    if _metrics.enabled():
        _metrics.gauge(
            "xmem_kernel_vmem_bytes",
            "Estimated per-invocation VMEM of a verified Pallas kernel",
            kernel=kernel[:120]).set(entry["vmem_bytes"])


def kernel_estimates() -> List[Dict[str, Any]]:
    """Snapshot of recorded kernel VMEM estimates, largest first."""
    with _lock:
        vals = [dict(v) for v in _KERNELS.values()]
    vals.sort(key=lambda e: -e["vmem_bytes"])
    return vals


# ---------------------------------------------------------------------------
# Long-lived HBM reservations (fed by serving/kv_cache — preallocated
# pools that memory_analysis() of any single executable cannot see; a
# capacity plan must add them to the static peaks)
# ---------------------------------------------------------------------------

_RESERVATIONS: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()


def record_reservation(name: str, nbytes: int, **detail) -> None:
    """Record (or update, keyed by name) one long-lived HBM reservation
    — e.g. the paged-KV pools.  ``nbytes <= 0`` drops the entry (the
    pool was released)."""
    with _lock:
        if nbytes <= 0:
            _RESERVATIONS.pop(name, None)
        else:
            entry = {"name": name, "bytes": int(nbytes)}
            entry.update(detail)
            _RESERVATIONS[name] = entry
    if _metrics.enabled():
        _metrics.gauge(
            "xmem_reserved_bytes",
            "Long-lived HBM reservation (paged-KV pools etc.)",
            pool=name[:120]).set(max(int(nbytes), 0))


def reservations() -> List[Dict[str, Any]]:
    """Snapshot of live reservations, largest first."""
    with _lock:
        vals = [dict(v) for v in _RESERVATIONS.values()]
    vals.sort(key=lambda e: -e["bytes"])
    return vals


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def summary_lines(top: int = 8) -> List[str]:
    """The "Memory" block of Profiler.summary_table(): one row per
    captured executable, largest static peak first."""
    with _lock:
        vals = sorted(_STORE.values(), key=lambda p: -p["peak_bytes"])
        kernels = sorted(_KERNELS.values(),
                         key=lambda e: -e["vmem_bytes"])
    res_lines = [f"  reserved {r['name'][:34]:<34}"
                 f"{_fmt_bytes(r['bytes']):>12}"
                 for r in reservations()]
    lines = ["Memory"]
    if not vals:
        hint = ("  (no executables captured — set FLAGS_tpu_xmem or "
                "FLAGS_tpu_metrics before compiling)")
        lines.append(hint)
        return lines + _kernel_lines(kernels, top) + res_lines
    lines.append(f"  executables: {len(vals)}  "
                 f"(static peaks from compiled.memory_analysis)")
    header = (f"  {'Executable':<38}{'PeakHBM':>12}{'Temp':>12}"
              f"{'Args':>12}{'FLOPs':>12}")
    lines.append(header)
    for p in vals[:top]:
        label = f"{p['source']}:{p['name']}"
        lines.append(f"  {label[:38]:<38}"
                     f"{_fmt_bytes(p['peak_bytes']):>12}"
                     f"{_fmt_bytes(p['temp_bytes']):>12}"
                     f"{_fmt_bytes(p['argument_bytes']):>12}"
                     f"{p['flops']:>12.3g}")
    if len(vals) > top:
        lines.append(f"  ... {len(vals) - top} more "
                     f"(xmem.profiles() has all)")
    lines += _kernel_lines(kernels, top)
    lines += res_lines
    return lines


def _kernel_lines(kernels: List[Dict[str, Any]], top: int) -> List[str]:
    if not kernels:
        return []
    lines = [f"  Pallas kernels: {len(kernels)}  "
             f"(VMEM estimates from the Level-3 verifier)"]
    for e in kernels[:top]:
        budget = e.get("budget_bytes")
        verdict = ""
        if budget:
            verdict = (" OVER" if e["vmem_bytes"] > budget else " ok")
            verdict += f" (budget {_fmt_bytes(budget)})"
        lines.append(f"    {e['kernel'][:36]:<36}"
                     f"{_fmt_bytes(e['vmem_bytes']):>12}{verdict}")
    return lines


def reset():
    """Drop all captured profiles (tests / between benchmark cases)."""
    with _lock:
        _STORE.clear()
        _KERNELS.clear()
        _RESERVATIONS.clear()
