"""Compile / retrace observability.

JIT recompiles are the #1 silent TPU perf killer (PAPERS.md: the MPK and
Gemma-on-TPU serving writeups both lead with it): a python scalar that
changes every step, or a dtype/shape drift between calls, silently turns
a sub-millisecond cached dispatch into a multi-second XLA compile.
Reference analog: the reference stack logs program-cache misses from
program_translator's ConcreteProgram cache; here the ground truth is
jax's own telemetry.

Two sources feed one thread-safe store:

1. `jax.monitoring` listeners (installed once, process-wide) on the
   backend-compile / jaxpr-trace duration events and the compilation
   cache hit/miss events — ground truth for "did XLA compile and for
   how long".
2. `record_trace(fn_name, ...)` calls from the `paddle_tpu.jit` entry
   points — per-function attribution: a StaticFunction that sees a new
   (treedef, static-leaf, shape, dtype) signature records one trace;
   every trace after the first is a retrace.

`stats()` snapshots everything; `Profiler.summary_table()` renders it as
the "Compilation" section. When `FLAGS_tpu_metrics` is on the same
events mirror into the metrics registry (`jit_compiles_total`,
`jit_compile_seconds_total`, `jit_retraces_total{fn=...}`).
"""
from __future__ import annotations

import threading
from typing import Dict

from . import metrics as _metrics

__all__ = ["install", "installed", "record_trace", "stats", "reset",
           "compile_count", "compile_seconds"]

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_JAXPR_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_totals = {
    "compile_count": 0,
    "compile_seconds": 0.0,
    "trace_count": 0,
    "trace_seconds": 0.0,
    "persistent_cache_hits": 0,
    "persistent_cache_misses": 0,
}
# fn name -> {"traces": n, "retraces": n}
_functions: Dict[str, Dict[str, int]] = {}
_installed = [False]


def _on_duration(event: str, duration: float, **kwargs):
    if event == _BACKEND_COMPILE_EVENT:
        with _lock:
            _totals["compile_count"] += 1
            _totals["compile_seconds"] += duration
        if _metrics.enabled():
            _metrics.counter(
                "jit_compiles_total",
                "XLA backend compiles in this process").inc()
            _metrics.counter(
                "jit_compile_seconds_total",
                "Cumulative XLA backend compile seconds").inc(duration)
    elif event == _JAXPR_TRACE_EVENT:
        with _lock:
            _totals["trace_count"] += 1
            _totals["trace_seconds"] += duration


def _on_event(event: str, **kwargs):
    if event == _CACHE_HIT_EVENT:
        with _lock:
            _totals["persistent_cache_hits"] += 1
    elif event == _CACHE_MISS_EVENT:
        with _lock:
            _totals["persistent_cache_misses"] += 1


def install():
    """Register the jax.monitoring listeners (idempotent). Listener
    registration is append-only in jax, so this must run exactly once
    per process; the profiler package calls it at import."""
    if _installed[0]:
        return
    _installed[0] = True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:  # pragma: no cover - jax without monitoring
        _installed[0] = False


def installed() -> bool:
    return _installed[0]


def record_trace(fn_name: str):
    """One tracing-cache miss for `fn_name` (called by the jit entry
    points when a call signature is seen for the first time). The first
    trace of a function is its initial compile; later ones are
    retraces."""
    with _lock:
        entry = _functions.setdefault(fn_name,
                                      {"traces": 0, "retraces": 0})
        entry["traces"] += 1
        is_retrace = entry["traces"] > 1
        if is_retrace:
            entry["retraces"] += 1
    if _metrics.enabled():
        _metrics.counter("jit_traces_total",
                         "Traces per jitted function", fn=fn_name).inc()
        if is_retrace:
            _metrics.counter(
                "jit_retraces_total",
                "Tracing-cache misses after the first trace",
                fn=fn_name).inc()


def compile_count() -> int:
    return _totals["compile_count"]


def compile_seconds() -> float:
    return _totals["compile_seconds"]


def stats() -> dict:
    """Snapshot of compile totals + per-function trace attribution."""
    with _lock:
        out = dict(_totals)
        out["functions"] = {k: dict(v) for k, v in _functions.items()}
        out["retraces"] = sum(v["retraces"] for v in _functions.values())
    return out


def reset():
    """Zero all counters (tests / per-benchmark-case deltas)."""
    with _lock:
        for k in _totals:
            _totals[k] = 0 if isinstance(_totals[k], int) else 0.0
        _functions.clear()
