"""Numerics observability: NaN/Inf watchdog + first-bad-op localization.

Reference analog: paddle/fluid/framework/details/nan_inf_utils (the
FLAGS_check_nan_inf per-op output scan) and paddle.amp.debugging's
check_numerics / TensorCheckerConfig. On the TPU stack the failure mode
this exists for is bf16/fp16 divergence at scale: GradScaler can tell
you *that* a step produced non-finites, this module tells you *which
primitive* did, at which file:line.

Three layers:

1. **Watchdog sites** — `check_array`/`check_tree` host-side checks and
   the site registry (`sites()`): every named check point counts hits
   and non-finite hits, with a configurable action (warn/raise/collect).
   Gated by ``FLAGS_tpu_check_nan_inf`` with the same discipline as
   ``FLAGS_tpu_metrics``: the disabled path is one dict lookup plus a
   bool check (`enabled()`), nothing else.

2. **First-bad-op localization** — `localize(fn, *args)` traces ``fn``
   to a jaxpr and re-interprets it eqn-by-eqn on the same inputs,
   reporting the first primitive whose output goes non-finite (while
   its inputs were finite), with `source_info` file:line attribution.
   Recurses into nested pjit/custom-call sub-jaxprs so "the bad op is
   inside an inner jit" still resolves to the real primitive.

3. **Tensor-stats telemetry** — `note(name, value)` keeps the last
   value of named scalar stats (grad norms, loss scale, update ratio)
   for the Profiler "Numerics" section; the instrumented call sites
   (optimizer step, ClipGradByGlobalNorm, GradScaler, hapi train_batch)
   mirror the same numbers into the metrics registry.
"""
from __future__ import annotations

import math
import threading
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import flags as _flags

__all__ = ["enabled", "check_array", "check_tree", "localize", "watch",
           "record_site", "sites", "note", "last_stats", "collected",
           "clear_collected", "reset", "summary_lines",
           "NonFiniteError"]

# disabled-path contract (see metrics.py): one dict lookup + bool check
_FLAG_DICT = _flags._REGISTRY
_FLAG_NAME = "FLAGS_tpu_check_nan_inf"


def enabled() -> bool:
    """Whether the numerics watchdog is on (the only check hot paths pay)."""
    return bool(_FLAG_DICT.get(_FLAG_NAME, False))


class NonFiniteError(FloatingPointError):
    """Raised by a check site with action='raise'. Carries the structured
    report (``.report``) when localization ran."""

    def __init__(self, msg, report=None):
        super().__init__(msg)
        self.report = report


# ---------------------------------------------------------------------------
# site registry + last-value stats + collect buffer
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
# name -> {"hits": int, "nonfinite": int, "last": summary-dict|None}
_SITES: Dict[str, Dict[str, Any]] = {}
# name -> last recorded scalar (grad norms, loss scale, ...)
_LAST: Dict[str, float] = {}
# action='collect' findings, oldest first (bounded)
_COLLECTED: List[dict] = []
_COLLECT_CAP = 10000


def record_site(name: str, nonfinite: bool, summary: Optional[dict] = None):
    """Count a watchdog check at ``name``; remember the last non-finite
    summary so the Numerics section can show what went wrong."""
    with _LOCK:
        s = _SITES.get(name)
        if s is None:
            s = _SITES[name] = {"hits": 0, "nonfinite": 0, "last": None}
        s["hits"] += 1
        if nonfinite:
            s["nonfinite"] += 1
            if summary is not None:
                s["last"] = summary


def sites() -> Dict[str, Dict[str, Any]]:
    """Snapshot of the per-site hit counters."""
    with _LOCK:
        return {k: dict(v) for k, v in _SITES.items()}


def note(name: str, value) -> None:
    """Record the last value of a named numerics stat (cheap: one dict
    store). Callers gate on metrics/watchdog enablement themselves."""
    try:
        _LAST[name] = float(value)
    except (TypeError, ValueError):
        pass


def last_stats() -> Dict[str, float]:
    return dict(_LAST)


def collected() -> List[dict]:
    """Findings recorded by action='collect' sites, oldest first."""
    with _LOCK:
        return list(_COLLECTED)


def clear_collected():
    with _LOCK:
        _COLLECTED.clear()


def reset():
    """Drop all watchdog state (tests)."""
    with _LOCK:
        _SITES.clear()
        _LAST.clear()
        _COLLECTED.clear()


# ---------------------------------------------------------------------------
# host-side checking
# ---------------------------------------------------------------------------

def _summarize_array(arr) -> Optional[dict]:
    """Count NaN/Inf in a concrete array; None when fully finite (or not
    a floating array). Host-side only — callers must not pass tracers."""
    import numpy as np

    a = np.asarray(arr)
    if not np.issubdtype(a.dtype, np.floating):
        return None
    finite = np.isfinite(a)
    if bool(finite.all()):
        return None
    nan = int(np.isnan(a).sum())
    inf = int((~finite).sum()) - nan
    return {"nan": nan, "inf": inf, "size": int(a.size),
            "shape": list(a.shape), "dtype": str(a.dtype)}


def _dispatch(name, summary, action, report=None):
    msg = (f"numerics: non-finite values in {name!r}: "
           f"{summary['nan']} NaN, {summary['inf']} Inf out of "
           f"{summary['size']} ({summary['dtype']}{summary['shape']})")
    if report is not None:
        msg += f"; first bad op: {report.get('where', '?')}"
    if action == "raise":
        raise NonFiniteError(msg, report=report)
    if action == "collect":
        with _LOCK:
            if len(_COLLECTED) < _COLLECT_CAP:
                _COLLECTED.append({"name": name, **summary,
                                   "report": report})
        return
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def check_array(arr, name: str, action: str = "warn") -> bool:
    """Check one concrete array at the watchdog site ``name``. Returns
    True when non-finite values were found (unless action='raise', which
    raises NonFiniteError instead). No-op (dict lookup only) when the
    watchdog flag is off."""
    if not enabled():
        return False
    summary = _summarize_array(arr)
    record_site(name, summary is not None, summary)
    if summary is None:
        return False
    _dispatch(name, summary, action)
    return True


def check_tree(tree, name: str, action: str = "warn") -> bool:
    """check_array over every floating leaf of a pytree (Tensors ok)."""
    if not enabled():
        return False
    import jax

    from ..core.tensor import Tensor

    leaves = jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: isinstance(x, Tensor))
    found = False
    for i, leaf in enumerate(leaves):
        arr = leaf._array if isinstance(leaf, Tensor) else leaf
        if not hasattr(arr, "dtype"):
            continue
        if isinstance(arr, jax.core.Tracer):
            continue
        found = check_array(arr, f"{name}[{i}]" if len(leaves) > 1
                            else name, action) or found
    return found


# ---------------------------------------------------------------------------
# first-bad-op localization
# ---------------------------------------------------------------------------

def _eqn_where(eqn) -> str:
    """file:line (fn) attribution of a jaxpr eqn, best effort."""
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown>"


def _eqn_frame(eqn) -> Tuple[Optional[str], Optional[int]]:
    try:
        from jax._src import source_info_util
        fr = source_info_util.user_frame(eqn.source_info)
        if fr is not None:
            return fr.file_name, int(fr.start_line)
    except (ImportError, AttributeError, TypeError, ValueError) as e:
        # jax._src layout moves between versions; attribution is
        # best-effort garnish on the finding, never a reason to fail it
        import logging
        logging.getLogger(__name__).debug(
            "eqn frame attribution failed: %s", e)
    return None, None


def _is_float(x) -> bool:
    import numpy as np
    dt = getattr(x, "dtype", None)
    return dt is not None and np.issubdtype(dt, np.floating)


def _first_nonfinite(vals) -> Optional[Tuple[int, dict]]:
    for i, v in enumerate(vals):
        if not _is_float(v):
            continue
        s = _summarize_array(v)
        if s is not None:
            return i, s
    return None


_SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                    "body_jaxpr")


def _sub_jaxprs(eqn):
    """(ClosedJaxpr-like) sub-jaxprs a higher-order eqn carries, for
    recursion into pjit / custom_jvp / remat / cond bodies."""
    out = []
    for k in _SUBJAXPR_PARAMS:
        j = eqn.params.get(k)
        if j is not None:
            out.append(j)
    j = eqn.params.get("branches")
    if j:
        out.extend(j)
    return out


def _interpret(jaxpr, consts, args, path: str):
    """Eval ``jaxpr`` one eqn at a time; return (outvals, report|None)
    where report names the first primitive producing non-finite outputs
    from finite inputs. Evaluation continues after a finding so callers
    still get the function's outputs."""
    from jax.core import Literal

    env: Dict[Any, Any] = {}

    def read(v):
        return v.val if isinstance(v, Literal) else env[v]

    for v, c in zip(jaxpr.constvars, consts):
        env[v] = c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a

    report = None
    for idx, eqn in enumerate(jaxpr.eqns):
        invals = [read(v) for v in eqn.invars]
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        outvals = eqn.primitive.bind(*subfuns, *invals, **bind_params)
        if not eqn.primitive.multiple_results:
            outvals = [outvals]
        for var, val in zip(eqn.outvars, outvals):
            env[var] = val
        if report is not None:
            continue
        inputs_bad = _first_nonfinite(invals) is not None
        bad = _first_nonfinite(outvals)
        if bad is None or inputs_bad:
            # blame the op that *introduced* the non-finites; ops that
            # merely propagate them are downstream noise
            continue
        out_i, summary = bad
        sub = _sub_jaxprs(eqn)
        inner = None
        for sj in sub:
            # higher-order op: descend to the real primitive
            inner_jaxpr = getattr(sj, "jaxpr", sj)
            inner_consts = getattr(sj, "consts", getattr(sj, "literals", ()))
            try:
                _, inner = _interpret(inner_jaxpr, inner_consts, invals,
                                      f"{path}{eqn.primitive.name}/")
            except Exception:
                inner = None
            if inner is not None:
                break
        if inner is not None:
            report = inner
        else:
            file_name, line = _eqn_frame(eqn)
            report = {
                "primitive": eqn.primitive.name,
                "where": _eqn_where(eqn),
                "file": file_name,
                "line": line,
                "eqn_index": idx,
                "path": path + eqn.primitive.name,
                "eqn": str(eqn)[:200],
                "output_index": out_i,
                **summary,
            }
    return [read(v) for v in jaxpr.outvars], report


def localize(fn: Callable, *args, **kwargs) -> Optional[dict]:
    """Find the first primitive of ``fn(*args, **kwargs)`` whose output
    goes non-finite on these inputs.

    Re-interprets the function's jaxpr eqn-by-eqn (eagerly, un-jitted) —
    slow, but only ever run on demand after a watchdog tripped. Returns
    a report dict (primitive, where, file, line, nan/inf counts) or
    None when every intermediate stays finite. Non-finite *inputs* are
    reported as ``{"primitive": "<input>"}`` since no op is to blame.

    Accepts Tensors, jax arrays, or numpy arrays; ``fn`` may be a plain
    function, a to_static StaticFunction, or a bound method.
    """
    import jax

    from ..core.tensor import Tensor

    # unwrap to_static so we trace the underlying (converted) python fn
    inner = getattr(fn, "_converted_fn", None) or fn

    def array_fn(*arrs):
        t_args, t_kwargs = _rebuild(arrs)
        out = inner(*t_args, **t_kwargs)
        return tuple(
            x._array if isinstance(x, Tensor) else x
            for x in jax.tree_util.tree_leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor)))

    flat, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    import numpy as np
    arrays = []
    for leaf in flat:
        if isinstance(leaf, Tensor):
            arrays.append(leaf._array)
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            arrays.append(jax.numpy.asarray(leaf))
        else:
            arrays.append(leaf)

    dyn_idx = [i for i, a in enumerate(arrays) if hasattr(a, "dtype")]

    def _rebuild(dyn_arrays):
        full = list(arrays)
        for i, a in zip(dyn_idx, dyn_arrays):
            full[i] = Tensor(a) if isinstance(flat[i], Tensor) else a
        return jax.tree_util.tree_unflatten(treedef, full)

    dyn = [arrays[i] for i in dyn_idx]
    bad_in = _first_nonfinite(dyn)
    if bad_in is not None:
        i, summary = bad_in
        return {"primitive": "<input>", "where": f"input[{bad_in[0]}]",
                "file": None, "line": None, "eqn_index": -1,
                "path": "<input>", "eqn": "", "output_index": i, **summary}

    closed = jax.make_jaxpr(array_fn)(*dyn)
    _, report = _interpret(closed.jaxpr, closed.consts, dyn, "")
    return report


def watch(fn: Callable, name: Optional[str] = None,
          action: str = "raise") -> Callable:
    """Wrap ``fn`` so its outputs are watchdog-checked after every call;
    on non-finite outputs the jaxpr is re-interpreted to localize the
    first bad op, and the action fires with the report attached. With
    the flag off the wrapper costs one dict lookup per call."""
    import functools

    site = name or getattr(fn, "__qualname__",
                           getattr(fn, "__name__", "watched"))

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        out = fn(*args, **kwargs)
        if not enabled():
            return out
        summary = _tree_summary(out)
        record_site(site, summary is not None, summary)
        if summary is not None:
            report = None
            try:
                report = localize(fn, *args, **kwargs)
            except (TypeError, ValueError, RuntimeError, KeyError,
                    AttributeError) as e:
                # localization re-interprets the jaxpr and can fail on
                # inputs the original call handled — the finding must
                # still be dispatched, just without a culprit
                import logging
                logging.getLogger(__name__).debug(
                    "numerics localization failed at %s: %s", site, e)
            _dispatch(site, summary, action, report=report)
        return out

    return wrapper


def _tree_summary(tree) -> Optional[dict]:
    """First non-finite leaf summary of a pytree of concrete outputs."""
    import jax

    from ..core.tensor import Tensor

    for leaf in jax.tree_util.tree_leaves(
            tree, is_leaf=lambda x: isinstance(x, Tensor)):
        arr = leaf._array if isinstance(leaf, Tensor) else leaf
        if not hasattr(arr, "dtype") or isinstance(arr, jax.core.Tracer):
            continue
        if not _is_float(arr):
            continue
        s = _summarize_array(arr)
        if s is not None:
            return s
    return None


# ---------------------------------------------------------------------------
# Profiler "Numerics" section
# ---------------------------------------------------------------------------

_STAT_ORDER = ("grad_global_norm", "grad_global_norm_preclip",
               "grad_global_norm_postclip", "param_global_norm",
               "weight_update_ratio", "loss_scale", "train_loss")


def summary_lines() -> List[str]:
    lines = [f"Numerics  (FLAGS_tpu_check_nan_inf="
             f"{'on' if enabled() else 'off'})"]
    with _LOCK:
        site_items = sorted(_SITES.items())
        stats = dict(_LAST)
        n_collected = len(_COLLECTED)
    # quantization-error gauges (quant_err_* from quantize_params /
    # convert_to_mixed_precision) group under their own sub-block so a
    # bad scale is localized like a NaN
    quant = [k for k in sorted(stats) if k.startswith("quant_err_")]
    shown = [k for k in _STAT_ORDER if k in stats]
    shown += [k for k in sorted(stats)
              if k not in _STAT_ORDER and k not in quant]
    for k in shown:
        v = stats[k]
        mark = "  <-- NON-FINITE" if not math.isfinite(v) else ""
        lines.append(f"  {k:<28} {v:.6g}{mark}")
    if quant:
        lines.append("  Quantization")
        for k in quant:
            v = stats[k]
            mark = "  <-- NON-FINITE" if not math.isfinite(v) else ""
            lines.append(f"    {k:<28} {v:.6g}{mark}")
    if site_items:
        lines.append(f"  check sites: {len(site_items)}")
        for nm, s in site_items[:10]:
            mark = "  <-- NON-FINITE" if s["nonfinite"] else ""
            lines.append(f"    {nm[:44]:<44} {s['hits']:>7} hits "
                         f"{s['nonfinite']:>5} bad{mark}")
    if n_collected:
        lines.append(f"  collected findings: {n_collected} "
                     f"(numerics.collected())")
    return lines
