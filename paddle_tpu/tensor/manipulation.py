"""Shape/layout manipulation ops.

Reference analog: python/paddle/tensor/manipulation.py (reshape/concat/
split/gather/scatter/...), PHI kernels paddle/phi/kernels/*/concat_kernel*
etc. All static-shape jnp lowerings so everything stays jit/MXU friendly.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "unsqueeze", "transpose",
    "concat", "stack", "split", "chunk", "unstack", "unbind", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "flip",
    "rot90", "roll", "gather", "gather_nd", "scatter", "scatter_",
    "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "index_add",
    "index_put", "masked_select", "masked_fill", "where", "take_along_axis",
    "put_along_axis", "cast", "slice", "pad", "repeat_interleave",
    "moveaxis", "swapaxes", "as_complex", "as_real", "view", "view_as",
    "atleast_1d", "atleast_2d", "atleast_3d", "unfold", "tensordot",
    "numel", "shard_index", "crop", "fill_diagonal_",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(v) for v in shape.tolist()]
    out = []
    for s in shape:
        out.append(int(s._array) if isinstance(s, Tensor) else int(s))
    return out


def reshape(x, shape, name=None):
    x = _ensure_tensor(x)
    shape = _shape_list(shape)
    # paddle semantics: 0 means "copy this dim from input"
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return apply_op(lambda a: jnp.reshape(a, shape), x, op_name="reshape")


def reshape_(x, shape, name=None):
    from ..core.tensor import rebind_inplace, tape_snapshot
    return rebind_inplace(x, reshape(tape_snapshot(x), shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = x.shape[:s] + [-1] + x.shape[e + 1:]
    return apply_op(lambda a: jnp.reshape(a, new_shape), x, op_name="flatten")


def squeeze(x, axis=None, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply_op(_f, x, op_name="squeeze")


def unsqueeze(x, axis, name=None):
    x = _ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._array) if isinstance(a, Tensor) else int(a) for a in axes]

    def _f(a):
        out = a
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply_op(_f, x, op_name="unsqueeze")


def transpose(x, perm, name=None):
    x = _ensure_tensor(x)
    perm = [int(p) for p in perm]
    return apply_op(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


def moveaxis(x, source, destination, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.moveaxis(a, source, destination), x,
                    op_name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), x,
                    op_name="swapaxes")


def concat(x, axis=0, name=None):
    tensors = [_ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors,
                    op_name="concat")


def stack(x, axis=0, name=None):
    tensors = [_ensure_tensor(t) for t in x]
    return apply_op(lambda *arrs: jnp.stack(arrs, axis=axis), *tensors,
                    op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    x = _ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            from ..core.errors import InvalidArgumentError
            raise InvalidArgumentError(
                f"paddle.split: dimension {dim} at axis {axis} is not "
                f"divisible by num_or_sections={num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_unknown = sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def _f(a):
        return tuple(lax.slice_in_dim(a, o, o + s, axis=axis)
                     for o, s in zip(offsets, sizes))
    return list(apply_op(_f, x, op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unstack(x, axis=0, num=None, name=None):
    x = _ensure_tensor(x)
    n = num or x.shape[axis]

    def _f(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))
    return list(apply_op(_f, x, op_name="unstack"))


def unbind(x, axis=0):
    return unstack(x, axis)


def tile(x, repeat_times, name=None):
    x = _ensure_tensor(x)
    reps = _shape_list(repeat_times)
    return apply_op(lambda a: jnp.tile(a, reps), x, op_name="tile")


def expand(x, shape, name=None):
    x = _ensure_tensor(x)
    shape = _shape_list(shape)
    xs = x.shape
    full = list(shape)
    off = len(full) - len(xs)
    for i, s in enumerate(full):
        if s == -1:
            full[i] = xs[i - off] if i >= off else 1
    return apply_op(lambda a: jnp.broadcast_to(a, full), x, op_name="expand")


def expand_as(x, y, name=None):
    return expand(x, _ensure_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    tensors = [_ensure_tensor(t) for t in inputs]
    outs = apply_op(lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)),
                    *tensors, op_name="broadcast_tensors")
    return list(outs)


def flip(x, axis, name=None):
    x = _ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda a: jnp.flip(a, axis=tuple(axes)), x, op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x,
                    op_name="rot90")


def roll(x, shifts, axis=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.roll(a, shifts, axis=axis), x, op_name="roll")


def gather(x, index, axis=0, name=None):
    x, index = _ensure_tensor(x), _ensure_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i,
                                          axis=axis), x, index, op_name="gather")


def gather_nd(x, index, name=None):
    x, index = _ensure_tensor(x), _ensure_tensor(index)

    def _f(a, idx):
        k = idx.shape[-1]
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a[flat_idx]
    return apply_op(_f, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    x = _ensure_tensor(x)
    index = _ensure_tensor(index)
    updates = _ensure_tensor(updates)

    def _f(a, idx, upd):
        idx = idx.reshape(-1)
        if overwrite:
            return a.at[idx].set(upd)
        zeroed = a.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return apply_op(_f, x, index, updates, op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    from ..core.tensor import rebind_inplace, tape_snapshot
    return rebind_inplace(x, scatter(tape_snapshot(x), index, updates,
                                     overwrite))


def scatter_nd(index, updates, shape, name=None):
    index = _ensure_tensor(index)
    updates = _ensure_tensor(updates)
    shape = _shape_list(shape)

    def _f(idx, upd):
        z = jnp.zeros(shape, upd.dtype)
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return z.at[flat_idx].add(upd)
    return apply_op(_f, index, updates, op_name="scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    x = _ensure_tensor(x)
    index = _ensure_tensor(index)
    updates = _ensure_tensor(updates)

    def _f(a, idx, upd):
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[flat_idx].add(upd)
    return apply_op(_f, x, index, updates, op_name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    x, index = _ensure_tensor(x), _ensure_tensor(index)
    return apply_op(lambda a, i: jnp.take(a, i, axis=axis), x, index,
                    op_name="index_select")


def index_sample(x, index):
    x, index = _ensure_tensor(x), _ensure_tensor(index)
    return apply_op(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
        x, index, op_name="index_sample")


def index_add(x, index, axis, value, name=None):
    x, index, value = _ensure_tensor(x), _ensure_tensor(index), _ensure_tensor(value)

    def _f(a, i, v):
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = am.at[i].add(vm)
        return jnp.moveaxis(out, 0, axis)
    return apply_op(_f, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    x = _ensure_tensor(x)
    value = _ensure_tensor(value)
    idx_tensors = [_ensure_tensor(i) for i in indices]

    def _f(a, v, *idxs):
        if accumulate:
            return a.at[tuple(idxs)].add(v)
        return a.at[tuple(idxs)].set(v)
    return apply_op(_f, x, value, *idx_tensors, op_name="index_put")


def masked_select(x, mask, name=None):
    # Dynamic-shaped output: eager-only (not jit-safe), matches reference
    # semantics; under jit use `where` instead.
    x, mask = _ensure_tensor(x), _ensure_tensor(mask)
    arr = np.asarray(x._array)[np.asarray(mask._array)]
    return Tensor(jnp.asarray(arr), stop_gradient=x.stop_gradient)


def masked_fill(x, mask, value, name=None):
    x, mask = _ensure_tensor(x), _ensure_tensor(mask)
    v = value._array if isinstance(value, Tensor) else value
    return apply_op(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a),
                    x, mask, op_name="masked_fill")


def where(condition, x=None, y=None, name=None):
    condition = _ensure_tensor(condition)
    if x is None and y is None:
        from .search import nonzero
        return nonzero(condition, as_tuple=True)
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                    op_name="where")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = _ensure_tensor(arr), _ensure_tensor(indices)
    return apply_op(lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                    arr, indices, op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    arr, indices = _ensure_tensor(arr), _ensure_tensor(indices)
    values = _ensure_tensor(values)

    def _f(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        am = jnp.moveaxis(a, axis, 0)
        im = jnp.moveaxis(i, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        other = tuple(jnp.indices(im.shape)[1:])
        if reduce == "assign":
            out = am.at[(im,) + other].set(vm)
        elif reduce == "add":
            out = am.at[(im,) + other].add(vm)
        elif reduce in ("mul", "multiply"):
            out = am.at[(im,) + other].multiply(vm)
        else:
            raise ValueError(f"unsupported reduce {reduce}")
        return jnp.moveaxis(out, 0, axis)
    return apply_op(_f, arr, indices, values, op_name="put_along_axis")


def cast(x, dtype):
    from ..core import dtype as dtype_mod
    x = _ensure_tensor(x)
    dt = dtype_mod.convert_dtype(dtype)
    return apply_op(lambda a: a.astype(dt), x, op_name="cast")


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    x = _ensure_tensor(x)

    def _v(s):
        return int(s._array) if isinstance(s, Tensor) else int(s)

    def _f(a):
        out = a
        for ax, st, en in zip(axes, starts, ends):
            n = a.shape[ax]
            st_, en_ = _v(st), _v(en)
            st_ = n + st_ if st_ < 0 else st_
            en_ = n + en_ if en_ < 0 else en_
            en_ = min(en_, n)
            out = lax.slice_in_dim(out, st_, en_, axis=ax)
        return out
    return apply_op(_f, x, op_name="slice")


def crop(x, shape=None, offsets=None, name=None):
    x = _ensure_tensor(x)
    shape = _shape_list(shape)
    offsets = [0] * x.ndim if offsets is None else _shape_list(offsets)
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]
    return apply_op(lambda a: lax.dynamic_slice(a, offsets, shape), x,
                    op_name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    x = _ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle nn.functional semantics: pad applies to last len(pad)//2 dims
        # ordered from the last spatial dim inward, honoring data_format.
        k = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format.endswith("C"):  # NHWC / NDHWC / NLC
            dims = list(range(1, 1 + k))
        else:
            dims = list(range(nd - k, nd))
        for i, d in enumerate(reversed(dims)):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def _f(a):
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)
    return apply_op(_f, x, op_name="pad")


def repeat_interleave(x, repeats, axis=None, name=None):
    x = _ensure_tensor(x)
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._array)
        arr = np.repeat(np.asarray(x._array), reps, axis=axis)
        return Tensor(jnp.asarray(arr), stop_gradient=x.stop_gradient)
    return apply_op(
        lambda a: jnp.repeat(a.reshape(-1) if axis is None else a,
                             repeats, axis=0 if axis is None else axis),
        x, op_name="repeat_interleave")


def as_complex(x, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: lax.complex(a[..., 0], a[..., 1]), x,
                    op_name="as_complex")


def as_real(x, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                    x, op_name="as_real")


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, _ensure_tensor(x), op_name="atleast_1d")
            for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, _ensure_tensor(x), op_name="atleast_2d")
            for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, _ensure_tensor(x), op_name="atleast_3d")
            for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def unfold(x, axis, size, step, name=None):
    x = _ensure_tensor(x)
    ax = axis % x.ndim

    def _f(a):
        n = a.shape[ax]
        starts = jnp.arange(0, n - size + 1, step)
        def one(s):
            return lax.dynamic_slice_in_dim(a, s, size, axis=ax)
        out = jax_vmap_stack(one, starts)       # [num, ..., size@ax+1, ...]
        out = jnp.moveaxis(out, 0, ax)          # [..., num@ax, size@ax+1,..]
        return jnp.moveaxis(out, ax + 1, -1)    # paddle: size appended last
    return apply_op(_f, x, op_name="unfold")


def jax_vmap_stack(fn, xs):
    import jax
    return jax.vmap(fn)(xs)


def tensordot(x, y, axes=2, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y,
                    op_name="tensordot")


def numel(x, name=None):
    x = _ensure_tensor(x)
    return Tensor(jnp.asarray(x.size, jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    input = _ensure_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards

    def _f(a):
        shard = a // shard_size
        in_shard = shard == shard_id
        return jnp.where(in_shard, a % shard_size, ignore_value)
    return apply_op(_f, input, op_name="shard_index")


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    x = _ensure_tensor(x)
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n - (offset if offset > 0 else 0))
    arr = x._array.at[..., idx + max(-offset, 0), idx + max(offset, 0)].set(value)
    x._set_array(arr)
    return x


for _n in __all__:
    if _n not in ("reshape_", "view", "view_as"):
        register(_n, globals()[_n])
