"""Tensor creation ops.

Reference analog: python/paddle/tensor/creation.py (full_like/ones/zeros/
arange/linspace/eye/empty/tril/triu/meshgrid/diag/...), lowered to jnp
instead of fill_constant-family PHI kernels.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, to_tensor
from ..core import dtype as dtype_mod
from ..ops.registry import register, _ensure_tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "tril", "triu", "meshgrid", "diag", "diagflat", "diag_embed",
    "assign", "clone", "tril_indices", "triu_indices", "complex",
    "create_parameter",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._array) if isinstance(s, Tensor) else int(s) for s in shape]


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = default or dtype_mod.get_default_dtype()
    return d


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int64
        else:
            dtype = dtype_mod.get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = _ensure_tensor(x)
    return Tensor(jnp.zeros_like(x._array, dtype=dtype_mod.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    x = _ensure_tensor(x)
    return Tensor(jnp.ones_like(x._array, dtype=dtype_mod.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = _ensure_tensor(x)
    return Tensor(jnp.full_like(x._array, fill_value,
                                dtype=dtype_mod.convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = jnp.int64
        else:
            dtype = dtype_mod.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_dt(dtype, jnp.dtype(jnp.float32))))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=_dt(dtype, jnp.dtype(jnp.float32))))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def tril(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.tril(a, diagonal), _ensure_tensor(x),
                    op_name="tril")


def triu(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.triu(a, diagonal), _ensure_tensor(x),
                    op_name="triu")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    tensors = [_ensure_tensor(a) for a in args]
    outs = apply_op(lambda *arrs: tuple(jnp.meshgrid(*arrs, indexing="ij")),
                    *tensors, op_name="meshgrid")
    return list(outs)


def diag(x, offset=0, padding_value=0, name=None):
    x = _ensure_tensor(x)

    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=jnp.bool_), k=offset)
                out = jnp.where(mask, out, jnp.asarray(padding_value, a.dtype))
            return out
        return jnp.diagonal(a, offset=offset)
    return apply_op(_diag, x, op_name="diag")


def diagflat(x, offset=0, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.diagflat(a, k=offset), x, op_name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    x = _ensure_tensor(x)

    def _emb(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        rows = idx + max(-offset, 0)
        cols = idx + max(offset, 0)
        out = base.at[..., rows, cols].set(a)
        if (dim1, dim2) not in ((-2, -1), (a.ndim - 1, a.ndim)):
            perm = list(range(out.ndim - 2))
            perm.insert(dim1 if dim1 >= 0 else out.ndim + dim1, out.ndim - 2)
            perm.insert(dim2 if dim2 >= 0 else out.ndim + dim2, out.ndim - 1)
        return out
    return apply_op(_emb, x, op_name="diag_embed")


def assign(x, output=None):
    x = _ensure_tensor(x) if not isinstance(x, (list, tuple, np.ndarray, int, float, bool)) else to_tensor(x)
    out = apply_op(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.number) else a,
                   x, op_name="assign")
    if output is not None:
        output._set_array(out._array)
        return output
    return out


def clone(x, name=None):
    return _ensure_tensor(x).clone()


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, jnp.dtype(jnp.int32))))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, jnp.dtype(jnp.int32))))


def complex(real, imag, name=None):  # noqa: A001
    return apply_op(lambda r, i: jax_lax_complex(r, i), _ensure_tensor(real),
                    _ensure_tensor(imag), op_name="complex")


def jax_lax_complex(r, i):
    import jax.lax as lax
    return lax.complex(r, i)


def create_parameter(shape, dtype=None, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.create_parameter parity; returns a trainable leaf Tensor."""
    from ..nn.initializer import _resolve_initializer
    init = _resolve_initializer(attr, default_initializer, is_bias)
    arr = init(_shape_list(shape), _dt(dtype))
    t = Tensor(arr, stop_gradient=False)
    t.is_leaf_param = True
    t.persistable = True
    if name:
        t.name = name
    return t


for _n in __all__:
    if _n not in ("to_tensor",):
        register(_n, globals()[_n])
