"""paddle_tpu.tensor — the tensor op surface.

Reference analog: python/paddle/tensor/__init__.py plus the Tensor
method-patching done by python/paddle/fluid/dygraph/math_op_patch.py and
varbase_patch_methods.py: every public op is also installed as a Tensor
method, and Python operators are overloaded.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor, apply_op
from . import creation, math, logic, manipulation, linalg, search, random, \
    attribute, einsum as einsum_mod, extras
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .attribute import shape, rank  # noqa: F401

shape_op = shape  # legacy internal alias
from .einsum import einsum  # noqa: F401

from .math import (add, subtract, multiply, divide, floor_divide, mod, pow,
                   neg, abs)  # noqa: A004
from .logic import (equal, not_equal, greater_than, greater_equal, less_than,
                    less_equal)
from .manipulation import cast as _cast_fn


# ---------------------------------------------------------------------------
# Tensor method patching (math_op_patch analog)
# ---------------------------------------------------------------------------

_METHOD_SOURCES = [creation, math, logic, manipulation, linalg, search,
                   random, einsum_mod, extras]

# ops whose first arg isn't the tensor / that shouldn't become methods
_SKIP_METHODS = {
    "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace",
    "logspace", "eye", "meshgrid", "tril_indices", "triu_indices",
    "complex", "create_parameter", "rand", "randn", "randint", "randperm",
    "uniform", "normal", "gaussian", "standard_normal", "scatter_nd",
    "add_n", "multiplex", "broadcast_tensors", "multi_dot", "einsum",
    "searchsorted", "concat", "stack", "where",
    "create_array", "array_write", "array_read", "array_length",
    "broadcast_shape", "create_tensor", "set_printoptions",
}


def _install_methods():
    for modsrc in _METHOD_SOURCES:
        for name in getattr(modsrc, "__all__", []):
            if name in _SKIP_METHODS:
                continue
            fn = getattr(modsrc, name)
            if callable(fn) and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # aliases / special names
    Tensor.astype = lambda self, dtype: _cast_fn(self, dtype)
    Tensor.cast = _cast_fn
    Tensor.dim = lambda self: self.ndim
    Tensor.numel = lambda self: manipulation.numel(self)
    Tensor.dot = linalg.dot
    Tensor.matmul = linalg.matmul
    Tensor.mm = linalg.matmul
    Tensor.norm = linalg.norm
    Tensor.where = lambda self, x, y: manipulation.where(self, x, y)
    # inplace methods share the tape-aware extras implementations — one
    # semantics for paddle.add_(x, y) and x.add_(y)
    Tensor.add_ = extras.add_
    Tensor.subtract_ = extras.subtract_
    Tensor.multiply_ = extras.multiply_
    Tensor.scale_ = extras.scale_
    Tensor.zero_ = extras.zero_
    Tensor.fill_ = extras.fill_
    Tensor.clip_ = extras.clip_
    Tensor.exponential_ = random.exponential_
    Tensor.uniform_ = random.uniform_
    Tensor.normal_ = random.normal_
    Tensor.scatter_ = manipulation.scatter_
    Tensor.reshape_ = manipulation.reshape_
    Tensor.fill_diagonal_ = manipulation.fill_diagonal_
    Tensor.unbind = manipulation.unbind
    Tensor.cpu = lambda self: self
    Tensor.cuda = lambda self: self
    Tensor.tpu = lambda self: self
    Tensor.pin_memory = lambda self: self
    Tensor.contiguous = lambda self: self
    Tensor.is_contiguous = lambda self: True


def _arr(y):
    return y._array if isinstance(y, Tensor) else y


def _binop(fn, reverse=False):
    def method(self, other):
        if reverse:
            return fn(to_tensor(other) if not isinstance(other, Tensor)
                      else other, self)
        return fn(self, other)
    return method


def _install_operators():
    Tensor.__add__ = _binop(add)
    Tensor.__radd__ = _binop(add, reverse=True)
    Tensor.__sub__ = _binop(subtract)
    Tensor.__rsub__ = _binop(subtract, reverse=True)
    Tensor.__mul__ = _binop(multiply)
    Tensor.__rmul__ = _binop(multiply, reverse=True)
    Tensor.__truediv__ = _binop(divide)
    Tensor.__rtruediv__ = _binop(divide, reverse=True)
    Tensor.__floordiv__ = _binop(floor_divide)
    Tensor.__rfloordiv__ = _binop(floor_divide, reverse=True)
    Tensor.__mod__ = _binop(mod)
    Tensor.__rmod__ = _binop(mod, reverse=True)
    Tensor.__pow__ = _binop(pow)
    Tensor.__rpow__ = _binop(pow, reverse=True)
    Tensor.__matmul__ = _binop(linalg.matmul)
    Tensor.__rmatmul__ = _binop(linalg.matmul, reverse=True)
    Tensor.__neg__ = lambda self: neg(self)
    Tensor.__abs__ = lambda self: abs(self)
    Tensor.__eq__ = _binop(equal)
    Tensor.__ne__ = _binop(not_equal)
    Tensor.__lt__ = _binop(less_than)
    Tensor.__le__ = _binop(less_equal)
    Tensor.__gt__ = _binop(greater_than)
    Tensor.__ge__ = _binop(greater_equal)
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__and__ = _binop(_and)
    Tensor.__or__ = _binop(_or)
    Tensor.__xor__ = _binop(_xor)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem


def _and(x, y):
    if jnp.dtype(x.dtype) == jnp.bool_:
        return logic.logical_and(x, y)
    return math.bitwise_and(x, y)


def _or(x, y):
    if jnp.dtype(x.dtype) == jnp.bool_:
        return logic.logical_or(x, y)
    return math.bitwise_or(x, y)


def _xor(x, y):
    if jnp.dtype(x.dtype) == jnp.bool_:
        return logic.logical_xor(x, y)
    return math.bitwise_xor(x, y)


def _idx_conv(item):
    if isinstance(item, Tensor):
        return item._array
    if isinstance(item, tuple):
        return tuple(_idx_conv(i) for i in item)
    if isinstance(item, list):
        return jnp.asarray(item)
    return item


def _getitem(self, item):
    idx = _idx_conv(item)
    return apply_op(lambda a: a[idx], self, op_name="getitem")


def _setitem(self, item, value):
    from ..core.tensor import (apply_op, is_grad_enabled, rebind_inplace,
                               tape_snapshot)
    idx = _idx_conv(item)
    v_is_t = isinstance(value, Tensor)
    needs_grad = is_grad_enabled() and (
        not self.stop_gradient or (v_is_t and not value.stop_gradient))
    if not needs_grad:
        v = value._array if v_is_t else value
        self._set_array(self._array.at[idx].set(v))
        return
    # record as an in-place op so cotangents flow both to the pre-mutation
    # value (zeros at the overwritten slots) and to `value` (gathered)
    snap = tape_snapshot(self)
    if v_is_t:
        out = apply_op(lambda a, v: a.at[idx].set(v), snap, value,
                       op_name="setitem")
    else:
        out = apply_op(lambda a: a.at[idx].set(value), snap,
                       op_name="setitem")
    rebind_inplace(self, out)


_install_methods()
_install_operators()
