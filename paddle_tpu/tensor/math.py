"""Math ops.

Reference analog: python/paddle/tensor/math.py (plus ops.py activations),
backed there by PHI elementwise/reduce kernels
(paddle/phi/kernels/{cpu,gpu}/elementwise_*, reduce_*). Here every op is one
jnp call; XLA fuses chains of them into single TPU kernels, which replaces
the reference's hand-fused elementwise machinery.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..ops.registry import unary_op, binary_op, register, _ensure_tensor

__all__ = [
    # elementwise unary
    "abs", "neg", "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt",
    "rsqrt", "square", "sin", "cos", "tan", "asin", "acos", "atan", "sinh",
    "cosh", "tanh", "asinh", "acosh", "atanh", "erf", "erfinv", "floor",
    "ceil", "round", "trunc", "frac", "sign", "sgn", "reciprocal",
    "sigmoid", "logit", "digamma", "lgamma", "angle", "conj", "real",
    "imag", "deg2rad", "rad2deg", "i0", "isnan", "isinf", "isfinite",
    # elementwise binary
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "floor_mod", "pow", "maximum", "minimum", "fmax", "fmin",
    "atan2", "hypot", "heaviside", "copysign", "nextafter", "logaddexp",
    "gcd", "lcm", "ldexp", "inner", "outer", "kron",
    # bitwise
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift",
    # reductions / scans
    "sum", "mean", "prod", "nansum", "nanmean", "max", "min", "amax",
    "amin", "all", "any", "std", "var", "median", "quantile", "logsumexp",
    "count_nonzero", "cumsum", "cumprod", "cummax", "cummin",
    "logcumsumexp",
    # misc
    "scale", "clip", "lerp", "add_n", "multiplex", "trace", "diagonal",
    "diff", "stanh", "nan_to_num", "increment", "rsqrt_",
]

# ---------------------------------------------------------------------------
# elementwise unary
# ---------------------------------------------------------------------------
abs = unary_op("abs", jnp.abs)  # noqa: A001
neg = unary_op("neg", jnp.negative)
exp = unary_op("exp", jnp.exp)
expm1 = unary_op("expm1", jnp.expm1)
log = unary_op("log", jnp.log)
log2 = unary_op("log2", jnp.log2)
log10 = unary_op("log10", jnp.log10)
log1p = unary_op("log1p", jnp.log1p)
sqrt = unary_op("sqrt", jnp.sqrt)
rsqrt = unary_op("rsqrt", lax.rsqrt)
square = unary_op("square", jnp.square)
sin = unary_op("sin", jnp.sin)
cos = unary_op("cos", jnp.cos)
tan = unary_op("tan", jnp.tan)
asin = unary_op("asin", jnp.arcsin)
acos = unary_op("acos", jnp.arccos)
atan = unary_op("atan", jnp.arctan)
sinh = unary_op("sinh", jnp.sinh)
cosh = unary_op("cosh", jnp.cosh)
tanh = unary_op("tanh", jnp.tanh)
asinh = unary_op("asinh", jnp.arcsinh)
acosh = unary_op("acosh", jnp.arccosh)
atanh = unary_op("atanh", jnp.arctanh)
erf = unary_op("erf", lax.erf)
erfinv = unary_op("erfinv", lax.erf_inv)
floor = unary_op("floor", jnp.floor)
ceil = unary_op("ceil", jnp.ceil)
round = unary_op("round", jnp.round)  # noqa: A001
trunc = unary_op("trunc", jnp.trunc)
frac = unary_op("frac", lambda x: x - jnp.trunc(x))
sign = unary_op("sign", jnp.sign)
sgn = unary_op("sgn", jnp.sign)
reciprocal = unary_op("reciprocal", jnp.reciprocal)
sigmoid = unary_op("sigmoid", jax_sigmoid := lambda x: lax.logistic(x))
logit = unary_op("logit", lambda x: jnp.log(x / (1 - x)))
digamma = unary_op("digamma", lax.digamma)
lgamma = unary_op("lgamma", lax.lgamma)
angle = unary_op("angle", jnp.angle)
conj = unary_op("conj", jnp.conj)
real = unary_op("real", jnp.real)
imag = unary_op("imag", jnp.imag)
deg2rad = unary_op("deg2rad", jnp.deg2rad)
rad2deg = unary_op("rad2deg", jnp.rad2deg)
i0 = unary_op("i0", lambda x: jnp.i0(x))
isnan = unary_op("isnan", jnp.isnan)
isinf = unary_op("isinf", jnp.isinf)
isfinite = unary_op("isfinite", jnp.isfinite)

# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------
add = binary_op("add", jnp.add)
subtract = binary_op("subtract", jnp.subtract)
multiply = binary_op("multiply", jnp.multiply)
divide = binary_op("divide", jnp.true_divide)
floor_divide = binary_op("floor_divide", jnp.floor_divide)
mod = binary_op("mod", jnp.mod)
remainder = binary_op("remainder", jnp.remainder)
floor_mod = remainder
pow = binary_op("pow", jnp.power)  # noqa: A001
maximum = binary_op("maximum", jnp.maximum)
minimum = binary_op("minimum", jnp.minimum)
fmax = binary_op("fmax", jnp.fmax)
fmin = binary_op("fmin", jnp.fmin)
atan2 = binary_op("atan2", jnp.arctan2)
hypot = binary_op("hypot", jnp.hypot)
heaviside = binary_op("heaviside", jnp.heaviside)
copysign = binary_op("copysign", jnp.copysign)
nextafter = binary_op("nextafter", jnp.nextafter)
logaddexp = binary_op("logaddexp", jnp.logaddexp)
gcd = binary_op("gcd", jnp.gcd)
lcm = binary_op("lcm", jnp.lcm)
ldexp = binary_op("ldexp", jnp.ldexp)
inner = binary_op("inner", jnp.inner)
outer = binary_op("outer", jnp.outer)
kron = binary_op("kron", jnp.kron)

# bitwise
bitwise_and = binary_op("bitwise_and", jnp.bitwise_and)
bitwise_or = binary_op("bitwise_or", jnp.bitwise_or)
bitwise_xor = binary_op("bitwise_xor", jnp.bitwise_xor)
bitwise_not = unary_op("bitwise_not", jnp.bitwise_not)
bitwise_left_shift = binary_op("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = binary_op("bitwise_right_shift", jnp.right_shift)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _reduction(name, jfn):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        x = _ensure_tensor(x)
        kw = {}
        if dtype is not None:
            from ..core import dtype as dtype_mod
            kw["dtype"] = dtype_mod.convert_dtype(dtype)
        return apply_op(
            lambda a: jfn(a, axis=_axis(axis), keepdims=keepdim, **kw),
            x, op_name=name or op.__name__)
    op.__name__ = name
    register(name, op)
    return op


sum = _reduction("sum", jnp.sum)  # noqa: A001
mean = _reduction("mean", jnp.mean)
prod = _reduction("prod", jnp.prod)
nansum = _reduction("nansum", jnp.nansum)
nanmean = _reduction("nanmean", jnp.nanmean)


def _cmp_reduction(name, jfn):
    def op(x, axis=None, keepdim=False, name=None):
        x = _ensure_tensor(x)
        return apply_op(lambda a: jfn(a, axis=_axis(axis), keepdims=keepdim),
                        x, op_name=name or op.__name__)
    op.__name__ = name
    register(name, op)
    return op


max = _cmp_reduction("max", jnp.max)  # noqa: A001
min = _cmp_reduction("min", jnp.min)  # noqa: A001
amax = _cmp_reduction("amax", jnp.max)
amin = _cmp_reduction("amin", jnp.min)
all = _cmp_reduction("all", jnp.all)  # noqa: A001
any = _cmp_reduction("any", jnp.any)  # noqa: A001


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = _ensure_tensor(x)
    ddof = 1 if unbiased else 0
    return apply_op(lambda a: jnp.std(a, axis=_axis(axis), ddof=ddof,
                                      keepdims=keepdim), x, op_name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = _ensure_tensor(x)
    ddof = 1 if unbiased else 0
    return apply_op(lambda a: jnp.var(a, axis=_axis(axis), ddof=ddof,
                                      keepdims=keepdim), x, op_name="var")


def median(x, axis=None, keepdim=False, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim),
                    x, op_name="median")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim, method=interpolation),
                    x, op_name="quantile")


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = _ensure_tensor(x)
    import jax.scipy.special as jsp
    return apply_op(lambda a: jsp.logsumexp(a, axis=_axis(axis), keepdims=keepdim),
                    x, op_name="logsumexp")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.count_nonzero(a, axis=_axis(axis),
                                                keepdims=keepdim).astype(jnp.int64),
                    x, op_name="count_nonzero")


# ---------------------------------------------------------------------------
# scans
# ---------------------------------------------------------------------------

def cumsum(x, axis=None, dtype=None, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=int(axis))
    return apply_op(_f, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1))
        return jnp.cumprod(a, axis=int(dim))
    return apply_op(_f, x, op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    x = _ensure_tensor(x)

    def _f(a):
        ax = 0 if axis is None else int(axis)
        aa = a.reshape(-1) if axis is None else a
        vals = lax.associative_scan(jnp.maximum, aa, axis=ax)
        n = aa.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == (ax % aa.ndim) else 1
                                     for i in range(aa.ndim)])
        idx = jnp.broadcast_to(idx, aa.shape)
        eq = aa == vals
        inds = lax.associative_scan(jnp.maximum, jnp.where(eq, idx, -1), axis=ax)
        return vals, inds.astype(jnp.int64)
    return apply_op(_f, x, op_name="cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    x = _ensure_tensor(x)

    def _f(a):
        ax = 0 if axis is None else int(axis)
        aa = a.reshape(-1) if axis is None else a
        vals = lax.associative_scan(jnp.minimum, aa, axis=ax)
        n = aa.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == (ax % aa.ndim) else 1
                                     for i in range(aa.ndim)])
        idx = jnp.broadcast_to(idx, aa.shape)
        eq = aa == vals
        inds = lax.associative_scan(jnp.maximum, jnp.where(eq, idx, -1), axis=ax)
        return vals, inds.astype(jnp.int64)
    return apply_op(_f, x, op_name="cummin")


def logcumsumexp(x, axis=None, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        ax = 0 if axis is None else int(axis)
        aa = a.reshape(-1) if axis is None else a
        return lax.associative_scan(jnp.logaddexp, aa, axis=ax)
    return apply_op(_f, x, op_name="logcumsumexp")


# ---------------------------------------------------------------------------
# misc math
# ---------------------------------------------------------------------------

def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = _ensure_tensor(x)
    s = scale._array if isinstance(scale, Tensor) else scale

    def _f(a):
        if bias_after_scale:
            out = a * jnp.asarray(s, a.dtype) + jnp.asarray(bias, a.dtype)
        else:
            out = (a + jnp.asarray(bias, a.dtype)) * jnp.asarray(s, a.dtype)
        return out
    out = apply_op(_f, x, op_name="scale")
    if act == "relu":
        return apply_op(lambda a: jnp.maximum(a, 0), out, op_name="relu")
    return out


def clip(x, min=None, max=None, name=None):  # noqa: A002
    x = _ensure_tensor(x)
    mn = min._array if isinstance(min, Tensor) else min
    mx = max._array if isinstance(max, Tensor) else max
    return apply_op(lambda a: jnp.clip(a, mn, mx), x, op_name="clip")


def lerp(x, y, weight, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    if isinstance(weight, Tensor):
        return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight,
                        op_name="lerp")
    return apply_op(lambda a, b: a + weight * (b - a), x, y, op_name="lerp")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    tensors = [_ensure_tensor(t) for t in inputs]
    return apply_op(lambda *arrs: np_functools_reduce_add(arrs), *tensors,
                    op_name="add_n")


def np_functools_reduce_add(arrs):
    out = arrs[0]
    for a in arrs[1:]:
        out = out + a
    return out


def multiplex(inputs, index, name=None):
    tensors = [_ensure_tensor(t) for t in inputs]
    index = _ensure_tensor(index)

    def _f(idx, *arrs):
        stacked = jnp.stack(arrs, axis=0)
        sel = idx.reshape(-1).astype(jnp.int32)
        return stacked[sel, jnp.arange(stacked.shape[1])]
    return apply_op(_f, index, *tensors, op_name="multiplex")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                        axis2=axis2), x, op_name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                           axis2=axis2), x, op_name="diagonal")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = _ensure_tensor(x)
    extra = []
    if prepend is not None:
        extra.append(_ensure_tensor(prepend))
    if append is not None:
        extra.append(_ensure_tensor(append))

    def _f(a, *rest):
        i = 0
        pre = app = None
        if prepend is not None:
            pre = rest[i]; i += 1
        if append is not None:
            app = rest[i]
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return apply_op(_f, x, *extra, op_name="diff")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), x, op_name="stanh")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                             neginf=neginf), x,
                    op_name="nan_to_num")


def increment(x, value=1.0, name=None):
    from ..core.tensor import rebind_inplace, tape_snapshot
    x = _ensure_tensor(x)
    out = apply_op(lambda a: a + jnp.asarray(value, a.dtype),
                   tape_snapshot(x), op_name="increment")
    return rebind_inplace(x, out)


def rsqrt_(x):
    return _inplace(x, lambda a: lax.rsqrt(a))


def _inplace(x, f):
    x._set_array(f(x._array))
    return x


for _n in ["std", "var", "median", "quantile", "logsumexp", "cumsum",
           "cumprod", "cummax", "cummin", "logcumsumexp", "scale", "clip",
           "lerp", "add_n", "multiplex", "trace", "diagonal", "diff",
           "stanh", "nan_to_num", "increment", "count_nonzero"]:
    register(_n, globals()[_n])


def clip_by_norm(x, max_norm, name=None):
    """Scale x so its L2 norm is at most max_norm (reference:
    clip_by_norm op — the per-tensor half of gradient clipping)."""
    x = _ensure_tensor(x)
    return apply_op(
        lambda a: a * jnp.minimum(
            1.0, max_norm / jnp.maximum(
                jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2)),
                1e-12)).astype(a.dtype),
        x, op_name="clip_by_norm")


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize each slice along ``axis`` to have p-norm at most
    max_norm (reference: renorm op)."""
    x = _ensure_tensor(x)

    def _f(a):
        a32 = a.astype(jnp.float32)
        reduce_axes = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a32) ** p, axis=reduce_axes,
                        keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return (a32 * factor).astype(a.dtype)

    return apply_op(_f, x, op_name="renorm")


register("clip_by_norm", clip_by_norm)
register("renorm", renorm)
__all__ += ["clip_by_norm", "renorm"]
