"""Comparison / logical ops.

Reference analog: python/paddle/tensor/logic.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..ops.registry import binary_op, unary_op, register, _ensure_tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "isclose", "allclose", "equal_all", "is_empty", "is_tensor",
]

equal = binary_op("equal", jnp.equal)
not_equal = binary_op("not_equal", jnp.not_equal)
greater_than = binary_op("greater_than", jnp.greater)
greater_equal = binary_op("greater_equal", jnp.greater_equal)
less_than = binary_op("less_than", jnp.less)
less_equal = binary_op("less_equal", jnp.less_equal)
logical_and = binary_op("logical_and", jnp.logical_and)
logical_or = binary_op("logical_or", jnp.logical_or)
logical_xor = binary_op("logical_xor", jnp.logical_xor)
logical_not = unary_op("logical_not", jnp.logical_not)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan),
                    x, y, op_name="isclose")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                              equal_nan=equal_nan),
                    x, y, op_name="allclose")


def equal_all(x, y, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(
        lambda a, b: jnp.asarray(a.shape == b.shape and bool_all(a, b)),
        x, y, op_name="equal_all")


def bool_all(a, b):
    return jnp.all(a == b) if a.shape == b.shape else jnp.asarray(False)


def is_empty(x, name=None):
    x = _ensure_tensor(x)
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


for _n in ["isclose", "allclose", "equal_all", "is_empty"]:
    register(_n, globals()[_n])
