"""Einsum.

Reference analog: python/paddle/tensor/einsum.py (own planner over matmul/
reduce ops). Here it is jnp.einsum — XLA's dot_general handles the
contraction planning and MXU mapping.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = ["einsum"]


def einsum(equation, *operands):
    tensors = [_ensure_tensor(op) for op in operands]
    return apply_op(lambda *arrs: jnp.einsum(equation, *arrs), *tensors,
                    op_name="einsum")


register("einsum", einsum)
