"""Tensor attribute ops.

Reference analog: python/paddle/tensor/attribute.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod
from ..ops.registry import register, _ensure_tensor

__all__ = ["shape", "rank", "is_floating_point", "is_integer", "is_complex",
           "real", "imag"]


def shape(x):
    x = _ensure_tensor(x)
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def rank(x):
    x = _ensure_tensor(x)
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def is_floating_point(x):
    return dtype_mod.is_floating_point(_ensure_tensor(x).dtype)


def is_integer(x):
    return dtype_mod.is_integer(_ensure_tensor(x).dtype)


def is_complex(x):
    return dtype_mod.is_complex(_ensure_tensor(x).dtype)


from .math import real, imag  # noqa: E402  (re-export for paddle parity)

for _n in ["shape", "rank"]:
    register(_n, globals()[_n])
