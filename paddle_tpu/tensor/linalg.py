"""Linear algebra ops.

Reference analog: python/paddle/tensor/linalg.py (matmul at :137) with PHI
kernels over cuBLAS/cuSOLVER (paddle/phi/kernels/funcs/blas). Here matmul is
jnp.matmul — XLA lowers it straight onto the MXU with bf16/f32 accumulate —
and decompositions come from jnp.linalg (lowered to XLA's QR/SVD/Cholesky).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = [
    "matmul", "bmm", "dot", "mv", "t", "norm", "dist", "cond", "cross",
    "cholesky", "cholesky_solve", "qr", "svd", "inv", "det", "slogdet",
    "solve", "triangular_solve", "eig", "eigh", "eigvals", "eigvalsh",
    "matrix_power", "matrix_rank", "pinv", "lstsq", "lu", "multi_dot",
    "corrcoef", "cov", "householder_product", "matrix_transpose",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """paddle.matmul parity (python/paddle/tensor/linalg.py:137)."""
    x, y = _ensure_tensor(x), _ensure_tensor(y)

    def _f(a, b):
        if transpose_x:
            if a.ndim == 1:
                pass
            else:
                a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            if b.ndim == 1:
                pass
            else:
                b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return apply_op(_f, x, y, op_name="matmul")


def bmm(x, y, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(jnp.matmul, x, y, op_name="bmm")


def dot(x, y, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="dot")


def mv(x, vec, name=None):
    x, vec = _ensure_tensor(x), _ensure_tensor(vec)
    return apply_op(jnp.matmul, x, vec, op_name="mv")


def t(x, name=None):
    x = _ensure_tensor(x)
    if x.ndim > 2:
        raise ValueError("paddle.t only supports ndim<=2; use transpose")
    return apply_op(lambda a: a.T if a.ndim == 2 else a, x, op_name="t")


def matrix_transpose(x, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.swapaxes(a, -1, -2), x, op_name="matrix_transpose")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = _ensure_tensor(x)
    if p is None:
        p = 2 if axis is not None or True else "fro"

    def _f(a):
        if axis is None:
            flat = a.reshape(-1)
            if p in ("fro", 2, 2.0):
                return jnp.sqrt(jnp.sum(flat * flat)) if not keepdim else \
                    jnp.sqrt(jnp.sum(flat * flat)).reshape([1] * a.ndim)
            if p in ("inf", jnp.inf, float("inf")):
                return jnp.max(jnp.abs(flat))
            return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if isinstance(ax, tuple) or p == "fro":
            return jnp.linalg.norm(a, ord="fro" if p == "fro" else p,
                                   axis=ax, keepdims=keepdim)
        if p in ("inf", jnp.inf, float("inf")):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p in ("-inf", -jnp.inf, float("-inf")):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply_op(_f, x, op_name="norm")


def dist(x, y, p=2, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)

    def _f(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op(_f, x, y, op_name="dist")


def cond(x, p=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.linalg.cond(a, p=p), x, op_name="cond")


def cross(x, y, axis=9, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    if axis == 9:  # paddle default: first axis of length 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return apply_op(lambda a, b: jnp.cross(a, b, axis=axis), x, y,
                    op_name="cross")


def cholesky(x, upper=False, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        lo = jnp.linalg.cholesky(a)
        return jnp.swapaxes(lo, -1, -2) if upper else lo
    return apply_op(_f, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)

    def _f(b, chol):
        import jax.scipy.linalg as jsl
        return jsl.cho_solve((chol, not upper), b)
    return apply_op(_f, x, y, op_name="cholesky_solve")


def qr(x, mode="reduced", name=None):
    x = _ensure_tensor(x)
    q, r = apply_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x,
                    op_name="qr")
    return q, r


def svd(x, full_matrices=False, name=None):
    x = _ensure_tensor(x)
    outs = apply_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        x, op_name="svd")
    return outs


def inv(x, name=None):
    x = _ensure_tensor(x)
    return apply_op(jnp.linalg.inv, x, op_name="inv")


def det(x, name=None):
    x = _ensure_tensor(x)
    return apply_op(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    x = _ensure_tensor(x)
    outs = apply_op(lambda a: tuple(jnp.linalg.slogdet(a)), x,
                    op_name="slogdet")
    from .manipulation import stack
    return stack(list(outs), axis=0)


def solve(x, y, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)

    def _f(a, b):
        import jax.scipy.linalg as jsl
        return jsl.solve_triangular(a, b, lower=not upper,
                                    trans=1 if transpose else 0,
                                    unit_diagonal=unitriangular)
    return apply_op(_f, x, y, op_name="triangular_solve")


def eig(x, name=None):
    import numpy as np
    x = _ensure_tensor(x)
    w, v = np.linalg.eig(np.asarray(x._array))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    x = _ensure_tensor(x)
    outs = apply_op(lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=True)),
                    x, op_name="eigh")
    return outs


def eigvals(x, name=None):
    import numpy as np
    x = _ensure_tensor(x)
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x._array))))


def eigvalsh(x, UPLO="L", name=None):
    x = _ensure_tensor(x)
    return apply_op(jnp.linalg.eigvalsh, x, op_name="eigvalsh")


def matrix_power(x, n, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x,
                    op_name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.linalg.matrix_rank(a, rtol=tol), x,
                    op_name="matrix_rank")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                              hermitian=hermitian), x,
                    op_name="pinv")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    outs = apply_op(lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
                    x, y, op_name="lstsq")
    return outs


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl
    x = _ensure_tensor(x)
    # paddle returns LAPACK 1-based sequential-swap pivots
    # (reference: tensor/linalg.py lu); jax's lu_factor is 0-based
    lu_, piv = apply_op(
        lambda a: (lambda f: (f[0], (f[1] + 1).astype(jnp.int32)))(
            jsl.lu_factor(a)),
        x, op_name="lu")
    if get_infos:
        from .creation import zeros
        return lu_, piv, zeros([1], dtype="int32")
    return lu_, piv


def multi_dot(x, name=None):
    tensors = [_ensure_tensor(t) for t in x]
    return apply_op(lambda *arrs: jnp.linalg.multi_dot(arrs), *tensors,
                    op_name="multi_dot")


def corrcoef(x, rowvar=True, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x,
                    op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.cov(a, rowvar=rowvar,
                                      ddof=1 if ddof else 0), x, op_name="cov")


def householder_product(x, tau, name=None):
    x, tau = _ensure_tensor(x), _ensure_tensor(tau)

    def _f(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else eye
        for i in range(n - 1, -1, -1):
            v = a[..., :, i]
            mask = (jnp.arange(m) > i).astype(a.dtype)
            v = v * mask + (jnp.arange(m) == i).astype(a.dtype)
            vvt = jnp.einsum("...i,...j->...ij", v, v)
            h = eye - t_[..., i][..., None, None] * vvt
            q = jnp.matmul(h, q)
        return q[..., :, :n] if False else q[..., :m, :n]
    return apply_op(_f, x, tau, op_name="householder_product")


for _n in __all__:
    register(_n, globals()[_n])
