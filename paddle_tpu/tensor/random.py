"""Random sampling ops.

Reference analog: python/paddle/tensor/random.py (gaussian/uniform/randint/
randperm/multinomial/bernoulli/...). Keys come from the global Generator
(paddle_tpu.framework.random); under jit these ops bake the key drawn at
trace time — for traced training loops use nn.functional.dropout's seeded
path or pass explicit keys.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod
from ..framework.random import next_key
from ..ops.registry import register, _ensure_tensor

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "normal", "gaussian", "standard_normal", "poisson", "bernoulli",
    "multinomial", "exponential_", "uniform_", "normal_", "rand_like",
    "randn_like",
]


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(v) for v in shape.tolist()]
    if isinstance(shape, int):
        return [shape]
    return [int(s._array) if isinstance(s, Tensor) else int(s) for s in shape]


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    return d if d is not None else (default or dtype_mod.get_default_dtype())


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    dt = _dt(dtype)
    key = jax.random.PRNGKey(seed) if seed else next_key()
    arr = jax.random.normal(key, _shape_list(shape), dtype=dt) * std + mean
    return Tensor(arr)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._array if isinstance(mean, Tensor) else mean
        s = std._array if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        arr = jax.random.normal(next_key(), shp) * s + m
        return Tensor(arr)
    return gaussian(shape if shape is not None else [1], mean, std)


def standard_normal(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype=dtype)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    dt = _dt(dtype)
    key = jax.random.PRNGKey(seed) if seed else next_key()
    arr = jax.random.uniform(key, _shape_list(shape), dtype=dt,
                             minval=min, maxval=max)
    return Tensor(arr)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dt = _dt(dtype, jnp.dtype(jnp.int32))
    arr = jax.random.randint(next_key(), _shape_list(shape), low, high,
                             dtype=jnp.int32).astype(dt)
    return Tensor(arr)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = _ensure_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    arr = jax.random.permutation(next_key(), n)
    return Tensor(arr.astype(_dt(dtype, jnp.dtype(jnp.int64))))


def poisson(x, name=None):
    x = _ensure_tensor(x)
    arr = jax.random.poisson(next_key(), x._array).astype(x._array.dtype)
    return Tensor(arr)


def bernoulli(x, name=None):
    x = _ensure_tensor(x)
    arr = jax.random.bernoulli(next_key(), x._array).astype(x._array.dtype)
    return Tensor(arr)


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = _ensure_tensor(x)
    a = x._array
    p = a / jnp.sum(a, axis=-1, keepdims=True)
    key = next_key()
    if a.ndim == 1:
        out = jax.random.choice(key, a.shape[-1], (num_samples,),
                                replace=replacement, p=p)
    else:
        keys = jax.random.split(key, a.shape[0])
        out = jnp.stack([
            jax.random.choice(k, a.shape[-1], (num_samples,),
                              replace=replacement, p=pi)
            for k, pi in zip(keys, p)])
    return Tensor(out.astype(jnp.int64))


def rand_like(x, dtype=None, name=None):
    x = _ensure_tensor(x)
    return uniform(x.shape, dtype or x.dtype, 0.0, 1.0)


def randn_like(x, dtype=None, name=None):
    x = _ensure_tensor(x)
    return gaussian(x.shape, dtype=dtype or x.dtype)


# in-place variants (Tensor methods)

def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    x._set_array(jax.random.uniform(next_key(), x._array.shape,
                                    dtype=x._array.dtype, minval=min,
                                    maxval=max))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._set_array(jax.random.normal(next_key(), x._array.shape,
                                   dtype=x._array.dtype) * std + mean)
    return x


def exponential_(x, lam=1.0, name=None):
    x._set_array(jax.random.exponential(next_key(), x._array.shape,
                                        dtype=x._array.dtype) / lam)
    return x


for _n in __all__:
    register(_n, globals()[_n])
