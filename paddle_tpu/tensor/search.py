"""Search / sort ops.

Reference analog: python/paddle/tensor/search.py.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "nonzero", "searchsorted",
    "kthvalue", "mode", "unique", "unique_consecutive", "bucketize",
    "histogram", "bincount",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = _ensure_tensor(x)

    def _f(a):
        out = jnp.argmax(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(jnp.int64)
    return apply_op(_f, x, op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = _ensure_tensor(x)

    def _f(a):
        out = jnp.argmin(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(jnp.int64)
    return apply_op(_f, x, op_name="argmin")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        idx = jnp.argsort(a, axis=axis, stable=True,
                          descending=descending)
        return idx.astype(jnp.int64)
    return apply_op(_f, x, op_name="argsort")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        out = jnp.sort(a, axis=axis, stable=True, descending=descending)
        return out
    return apply_op(_f, x, op_name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    x = _ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())

    def _f(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = lax.top_k(moved, k)
        else:
            vals, idx = lax.top_k(-moved, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply_op(_f, x, op_name="topk")


def nonzero(x, as_tuple=False):
    # Dynamic-shape: eager-only, like reference's dynamic-output ops.
    x = _ensure_tensor(x)
    idx = np.nonzero(np.asarray(x._array))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64)))
                     for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    ss, v = _ensure_tensor(sorted_sequence), _ensure_tensor(values)

    def _f(s, x):
        side = "right" if right else "left"
        if s.ndim == 1:
            out = jnp.searchsorted(s, x, side=side)
        else:
            import jax
            flat_s = s.reshape(-1, s.shape[-1])
            flat_x = x.reshape(-1, x.shape[-1])
            out = jax.vmap(lambda a, b: jnp.searchsorted(a, b, side=side))(
                flat_s, flat_x).reshape(x.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply_op(_f, ss, v, op_name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        ax = axis % a.ndim
        vals = jnp.sort(a, axis=ax)
        idxs = jnp.argsort(a, axis=ax, stable=True)
        v = jnp.take(vals, k - 1, axis=ax)
        i = jnp.take(idxs, k - 1, axis=ax)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return v, i.astype(jnp.int64)
    return apply_op(_f, x, op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    x = _ensure_tensor(x)
    arr = np.asarray(x._array)
    ax = axis % arr.ndim
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], arr.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shp = moved.shape[:-1]
    v, ind = vals.reshape(shp), idxs.reshape(shp)
    if keepdim:
        v, ind = np.expand_dims(v, ax), np.expand_dims(ind, ax)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(ind))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = _ensure_tensor(x)
    arr = np.asarray(x._array)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    x = _ensure_tensor(x)
    arr = np.asarray(x._array)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
    else:
        diff = np.any(arr[1:] != arr[:-1],
                      axis=tuple(i for i in range(arr.ndim) if i != axis))
        keep = np.concatenate([[True], diff])
    out = arr[keep] if axis is None else np.compress(keep, arr, axis=axis)
    rets = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        rets.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.where(keep)[0]
        counts = np.diff(np.append(idx, len(keep)))
        rets.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return rets[0] if len(rets) == 1 else tuple(rets)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    input = _ensure_tensor(input)
    arr = np.asarray(input._array)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(hist.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    x = _ensure_tensor(x)
    w = _ensure_tensor(weights) if weights is not None else None
    arr = np.asarray(x._array)
    wa = np.asarray(w._array) if w is not None else None
    return Tensor(jnp.asarray(np.bincount(arr, weights=wa,
                                          minlength=minlength)))


for _n in __all__:
    register(_n, globals()[_n])
