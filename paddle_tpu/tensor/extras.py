"""Remaining paddle.tensor surface: inplace variants, TensorArray ops,
and assorted math/manipulation stragglers.

Reference analog: the `*_` inplace methods patched in
python/paddle/fluid/dygraph/varbase_patch_methods.py + math_op_patch.py,
tensor/array.py (array_read/array_write/array_length/create_array),
tensor/creation.py (create_tensor), tensor/math.py (addmm, frexp,
nanmedian, nanquantile...), tensor/manipulation.py (take, vsplit,
reverse, strided_slice...).

Inplace on a functional core: each `op_`(x, ...) applies the functional
op to a tape snapshot of x and rebinds x to the result, so autograd sees
a well-formed node (the reference's inplace-version-counter machinery
collapses to this snapshot/rebind pair — see core.tensor.tape_snapshot).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, tape_snapshot, rebind_inplace
from . import linalg as _linalg
from . import manipulation as _manip
from . import math as _math

__all__ = [
    # inplace (scatter_/reshape_/fill_diagonal_ live in manipulation.py,
    # uniform_/normal_/exponential_ in random.py — not duplicated here)
    "add_", "subtract_", "multiply_", "divide_", "ceil_", "clip_",
    "erfinv_", "exp_", "flatten_", "floor_", "index_add_", "lerp_",
    "put_along_axis_", "reciprocal_", "remainder_", "round_", "scale_",
    "sqrt_", "squeeze_", "tanh_", "unsqueeze_", "zero_", "fill_",
    # aliases & stragglers
    "mm", "inverse", "addmm", "frexp", "nanmedian", "nanquantile",
    "take", "vsplit", "hsplit", "dsplit", "reverse", "strided_slice",
    "broadcast_shape", "lu_unpack", "erfinv",
    "is_complex", "is_floating_point", "is_integer", "set_printoptions",
    # TensorArray (static-graph parity)
    "create_array", "array_write", "array_read", "array_length",
    "create_tensor",
]


# ---------------------------------------------------------------------------
# inplace machinery
# ---------------------------------------------------------------------------

def _inplace(fn):
    """Lift a functional op into its `op_` variant."""
    def op_(x, *args, **kwargs):
        snap = tape_snapshot(x)
        out = fn(snap, *args, **kwargs)
        rebind_inplace(x, out)
        return x
    return op_


add_ = _inplace(_math.add)
subtract_ = _inplace(_math.subtract)
multiply_ = _inplace(_math.multiply)
divide_ = _inplace(_math.divide)
ceil_ = _inplace(_math.ceil)
clip_ = _inplace(_math.clip)
exp_ = _inplace(_math.exp)
floor_ = _inplace(_math.floor)
lerp_ = _inplace(_math.lerp)
reciprocal_ = _inplace(_math.reciprocal)
remainder_ = _inplace(_math.remainder)
round_ = _inplace(_math.round)
scale_ = _inplace(_math.scale)
sqrt_ = _inplace(_math.sqrt)
tanh_ = _inplace(_math.tanh)
flatten_ = _inplace(_manip.flatten)
squeeze_ = _inplace(_manip.squeeze)
unsqueeze_ = _inplace(_manip.unsqueeze)
index_add_ = _inplace(_manip.index_add)
put_along_axis_ = _inplace(_manip.put_along_axis)


def zero_(x):
    """reference: varbase_patch_methods zero_."""
    x._set_array(jnp.zeros_like(x._array))
    return x


def fill_(x, value):
    x._set_array(jnp.full_like(x._array, value))
    return x


# ---------------------------------------------------------------------------
# math / linalg stragglers
# ---------------------------------------------------------------------------

def erfinv(x, name=None):
    """reference: tensor/math.py erfinv → phi erfinv kernel."""
    return apply_op(jax.scipy.special.erfinv, x, op_name="erfinv")


erfinv_ = _inplace(erfinv)


def mm(input, mat2, name=None):
    """Alias of matmul (reference: tensor/math.py mm)."""
    return _linalg.matmul(input, mat2)


def inverse(x, name=None):
    """Alias of linalg.inv (reference: tensor/math.py inverse)."""
    return _linalg.inv(x)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) — reference: tensor/math.py addmm."""
    return apply_op(
        lambda i, a, b: beta * i + alpha * (a @ b), input, x, y,
        op_name="addmm")


def frexp(x, name=None):
    """Mantissa/exponent decomposition (reference: tensor/math.py frexp).
    Returns (mantissa in ±[0.5, 1), exponent) with zeros mapping to
    (0, 0)."""
    def _f(a):
        af = a.astype(jnp.float32)
        exp = jnp.where(af == 0, 0,
                        jnp.floor(jnp.log2(jnp.abs(
                            jnp.where(af == 0, 1.0, af)))) + 1)
        mant = af / jnp.exp2(exp)
        return mant.astype(a.dtype), exp.astype(a.dtype)
    return apply_op(_f, x, op_name="frexp", n_outs=2)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), x,
        op_name="nanmedian")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.nanquantile(a, q, axis=axis, keepdims=keepdim)
        .astype(a.dtype), x, op_name="nanquantile")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu results into P, L, U
    (reference: tensor/linalg.py lu_unpack)."""
    def _unpack(lu_arr, piv_arr):
        m, n = lu_arr.shape[-2], lu_arr.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_arr[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_arr.dtype)
        U = jnp.triu(lu_arr[..., :k, :])
        # pivots (1-based sequential row swaps) → permutation matrix
        perm = jnp.broadcast_to(jnp.arange(m),
                                piv_arr.shape[:-1] + (m,)).copy()

        def apply_swaps(perm_row, piv_row):
            def body(i, p):
                j = piv_row[i] - 1
                pi, pj = p[i], p[j]
                return p.at[i].set(pj).at[j].set(pi)
            return jax.lax.fori_loop(0, piv_row.shape[0], body, perm_row)

        flat_perm = perm.reshape(-1, m)
        flat_piv = piv_arr.reshape(-1, piv_arr.shape[-1])
        out = jax.vmap(apply_swaps)(flat_perm, flat_piv)
        P = jax.nn.one_hot(out, m, dtype=lu_arr.dtype)
        P = jnp.swapaxes(P, -1, -2).reshape(lu_arr.shape[:-2] + (m, m))
        return P, L, U
    return apply_op(_unpack, x, y, op_name="lu_unpack", n_outs=3)


# ---------------------------------------------------------------------------
# manipulation stragglers
# ---------------------------------------------------------------------------

def take(x, index, mode="raise", name=None):
    """Flattened-index gather (reference: tensor/math.py take)."""
    assert mode in ("raise", "wrap", "clip")
    n_elems = int(np.prod(x.shape)) if isinstance(x, Tensor) \
        else int(np.asarray(x).size)
    idx_val = index._array if isinstance(index, Tensor) else index
    if mode == "raise" and not isinstance(idx_val, jax.core.Tracer):
        # eager host-side bounds check, matching the reference's error;
        # under jit the index is a tracer, so fall back to clip semantics
        idx_np = np.asarray(idx_val)
        if idx_np.size and (idx_np.min() < -n_elems
                            or idx_np.max() >= n_elems):
            raise ValueError(
                f"take(mode='raise'): index out of range for tensor with "
                f"{n_elems} elements (got min {idx_np.min()}, "
                f"max {idx_np.max()})")

    def _f(a, idx):
        flat = a.reshape(-1)
        if mode == "raise":
            idx = jnp.where(idx < 0, idx + n_elems, idx)
            return jnp.take(flat, idx.reshape(-1),
                            mode="clip").reshape(idx.shape)
        return jnp.take(flat, idx.reshape(-1),
                        mode=mode).reshape(idx.shape)
    return apply_op(_f, x, index, op_name="take")


def vsplit(x, num_or_sections, name=None):
    assert x.ndim >= 2, "vsplit expects ndim >= 2"
    return _manip.split(x, num_or_sections, axis=0)


def hsplit(x, num_or_sections, name=None):
    axis = 0 if x.ndim == 1 else 1
    return _manip.split(x, num_or_sections, axis=axis)


def dsplit(x, num_or_sections, name=None):
    assert x.ndim >= 3, "dsplit expects ndim >= 3"
    return _manip.split(x, num_or_sections, axis=2)


def reverse(x, axis, name=None):
    """Legacy alias of flip (reference: fluid.layers.reverse)."""
    return _manip.flip(x, axis)


def strided_slice(x, axes, starts, ends, strides, name=None):
    """reference: tensor/manipulation.py strided_slice."""
    def _f(a):
        idx = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice(s, e, st)
        return a[tuple(idx)]
    return apply_op(_f, x, op_name="strided_slice")


def broadcast_shape(x_shape, y_shape):
    """Pure shape math (reference: tensor/manipulation.py
    broadcast_shape)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ---------------------------------------------------------------------------
# dtype predicates & printing
# ---------------------------------------------------------------------------

def is_complex(x):
    return jnp.issubdtype(x._array.dtype if isinstance(x, Tensor)
                          else jnp.asarray(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x._array.dtype if isinstance(x, Tensor)
                          else jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x._array.dtype if isinstance(x, Tensor)
                          else jnp.asarray(x).dtype, jnp.integer)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: tensor/to_string.py set_printoptions — our Tensor repr
    renders through numpy, so numpy's printoptions are the single knob."""
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if sci_mode is not None:
        kwargs["suppress"] = not sci_mode
    np.set_printoptions(**kwargs)


# ---------------------------------------------------------------------------
# TensorArray parity (reference: tensor/array.py — LoDTensorArray ops).
# Dygraph-mode semantics: a plain python list of Tensors.
# ---------------------------------------------------------------------------

def create_array(dtype="float32", initialized_list=None):
    arr = list(initialized_list) if initialized_list else []
    for v in arr:
        assert isinstance(v, Tensor), \
            "create_array initialized_list must hold Tensors"
    return arr


def array_write(x, i, array=None):
    i = int(i.numpy()) if isinstance(i, Tensor) else int(i)
    if array is None:
        array = []
    if i < len(array):
        array[i] = x
    else:
        assert i == len(array), \
            f"array_write index {i} out of range {len(array)}"
        array.append(x)
    return array


def array_read(array, i):
    i = int(i.numpy()) if isinstance(i, Tensor) else int(i)
    return array[i]


def array_length(array):
    return len(array)


def create_tensor(dtype, name=None, persistable=False):
    """reference: tensor/creation.py create_tensor."""
    from ..core.dtype import convert_dtype
    return Tensor(jnp.zeros([], convert_dtype(dtype)))
