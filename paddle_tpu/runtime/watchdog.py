"""Phase watchdogs and deadline executors.

Reference analog: the reference's distributed runtime guards long host
operations with timeouts (phi TCPStore wait budgets, gloo/NCCL op
timeouts surfaced through ProcessGroup options); production TPU fleets
on preemptible capacity (PAPERS.md, Gemma-on-Cloud-TPU) additionally
treat *hangs* — a device claim that never returns, a compile that never
finishes, a collective a peer never enters — as routine failures that
must convert to a bounded-time, restartable error.

This module promotes bench.py's ad-hoc staged deadlines into a shared
subsystem:

``Watchdog``
    Named phases (``device_init``, ``compile``, ``first_step``,
    ``collective``, ``ckpt.commit``) with per-phase deadlines sourced
    from ``FLAGS_tpu_watchdog_*``. A synchronous state machine —
    ``begin``/``end``/``poll`` — with an injectable clock so expiry
    logic is unit-testable without real sleeps, plus an optional ticker
    thread for production. On expiry: faulthandler all-thread stack
    dump (the hang's smoking gun), ``watchdog_expired_total{phase=}``,
    a structured incident record, and a typed :class:`PhaseTimeout`.

``run_with_deadline``
    Daemon-thread executor: run ``fn`` with a wall-clock budget, raise
    :class:`PhaseTimeout` if it does not land. Generalizes bench.py's
    measure-thread watchdog.

``init_with_retries``
    Device/backend init with exponential backoff inside a window and
    fail-fast on a hung attempt (bench.py's ``_init_device_with_retries``
    now delegates here).

Incident records accumulate in a bounded module buffer (``incidents()``)
so bench.py and the Profiler "Health" section can report *what* hung
and *when* instead of silently carrying stale numbers forward.
"""
from __future__ import annotations

import atexit
import faulthandler
import json
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

__all__ = ["PhaseTimeout", "Watchdog", "run_with_deadline",
           "init_with_retries", "record_incident", "incidents",
           "clear_incidents", "last_incident", "persist_incidents",
           "incident_sidecar_path", "INCIDENT_SCHEMA", "PHASES", "phase",
           "global_watchdog"]

# canonical phases and the flag holding each deadline (seconds; <= 0
# disables that phase's deadline)
PHASES = {
    "device_init": "FLAGS_tpu_watchdog_device_init",
    "compile": "FLAGS_tpu_watchdog_compile",
    "first_step": "FLAGS_tpu_watchdog_first_step",
    "collective": "FLAGS_tpu_watchdog_collective",
    "ckpt.commit": "FLAGS_tpu_watchdog_ckpt_commit",
    "serve.step": "FLAGS_tpu_watchdog_serve_step",
}


class PhaseTimeout(TimeoutError):
    """A watched phase exceeded its deadline (the job is hung, not
    crashed — the caller decides whether to fall back, save, or exit
    101 into the elastic relaunch path)."""

    def __init__(self, phase: str, elapsed_s: float, deadline_s: float,
                 detail: str = ""):
        self.phase = phase
        self.elapsed_s = float(elapsed_s)
        self.deadline_s = float(deadline_s)
        self.detail = detail
        msg = (f"phase {phase!r} exceeded its {deadline_s:.1f}s deadline "
               f"(elapsed {elapsed_s:.1f}s)")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


# -- incident records --------------------------------------------------------
#
# Structured, bounded, in-process. The consumers: bench.py attaches the
# last incident to its JSON line, HealthMonitor/Profiler summarize them.

_INCIDENTS: List[Dict[str, Any]] = []
_INCIDENTS_MAX = 64
_INCIDENTS_LOCK = threading.Lock()
_PERSIST_REGISTERED = False

INCIDENT_SCHEMA = "paddle_tpu.incidents.v1"


def record_incident(kind: str, **fields) -> Dict[str, Any]:
    """Append a structured incident ``{kind, time, rank, **fields}``.
    The first record arms an atexit hook that persists the buffer to a
    JSONL sidecar, so incidents survive the process for
    ``tools/trace_report.py --incidents`` post-mortems (exit-101 paths
    bypass atexit and call :func:`persist_incidents` explicitly)."""
    rec = {"kind": kind, "time": time.time(),
           "rank": int(os.environ.get("PADDLE_TRAINER_ID", "0")),
           "pid": os.getpid()}
    rec.update(fields)
    global _PERSIST_REGISTERED
    with _INCIDENTS_LOCK:
        _INCIDENTS.append(rec)
        del _INCIDENTS[:-_INCIDENTS_MAX]
        if not _PERSIST_REGISTERED:
            _PERSIST_REGISTERED = True
            atexit.register(_persist_at_exit)
    from ..profiler import metrics
    if metrics.enabled():
        metrics.counter("health_incidents_total",
                        "Structured runtime-health incidents",
                        kind=kind).inc()
    return rec


def incidents() -> List[Dict[str, Any]]:
    with _INCIDENTS_LOCK:
        return list(_INCIDENTS)


def last_incident() -> Optional[Dict[str, Any]]:
    with _INCIDENTS_LOCK:
        return _INCIDENTS[-1] if _INCIDENTS else None


def clear_incidents():
    with _INCIDENTS_LOCK:
        del _INCIDENTS[:]


def incident_sidecar_path() -> str:
    """Where :func:`persist_incidents` writes by default:
    ``$PADDLE_TPU_INCIDENTS_OUT`` when set, else
    ``incidents_rank<N>.jsonl`` under ``$PADDLE_TPU_INCIDENT_DIR``
    (default: the current directory)."""
    explicit = os.environ.get("PADDLE_TPU_INCIDENTS_OUT")
    if explicit:
        return explicit
    base = os.environ.get("PADDLE_TPU_INCIDENT_DIR", ".")
    try:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:
        rank = 0
    return os.path.join(base, f"incidents_rank{rank}.jsonl")


def persist_incidents(path: Optional[str] = None) -> Optional[str]:
    """Flush the incident buffer to a JSONL sidecar (header line with
    the schema/rank/pid, then one incident per line; atomic tmp-file +
    rename). No-op when the buffer is empty. Called automatically at
    normal interpreter exit once an incident exists; exit-101 paths
    (``HealthMonitor._convert``, bench's never-exit-silent harness)
    call it explicitly because ``os._exit`` skips atexit."""
    recs = incidents()
    if not recs:
        return None
    path = path or incident_sidecar_path()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    header = {"schema": INCIDENT_SCHEMA, "pid": os.getpid(),
              "rank": recs[-1].get("rank", 0), "wall_time": time.time(),
              "count": len(recs)}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(header, sort_keys=True) + "\n")
        for rec in recs:
            f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")
    os.replace(tmp, path)
    return path


def _persist_at_exit():
    try:
        persist_incidents()
    except OSError as exc:  # read-only cwd etc. — losing the sidecar
        sys.stderr.write(f"watchdog: incident persist failed: {exc}\n")


def _dump_all_threads(reason: str):
    """faulthandler all-thread dump — where exactly is everyone stuck."""
    try:
        sys.stderr.write(f"watchdog: {reason}; all-thread stack dump:\n")
        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        sys.stderr.flush()
    # diagnostics must never mask the timeout being reported (stderr may
    # be a closed pipe under a dying launcher)
    except Exception:  # tpu-lint: disable=except-pass
        pass


def _expired_metric(phase: str):
    from ..profiler import metrics
    if metrics.enabled():
        metrics.counter("watchdog_expired_total",
                        "Phase-deadline expiries", phase=phase).inc()


class Watchdog:
    """Deadline bookkeeping for named phases.

    Synchronous core: ``begin(phase)`` arms a deadline, ``end(phase)``
    disarms and returns the elapsed time, ``poll()`` expires overdue
    phases (dump + metric + incident + ``on_expire`` callback, then
    raises :class:`PhaseTimeout` unless ``raise_on_expire=False``).
    ``clock`` is injectable so tests drive expiry without sleeping.

    Production use arms a ticker thread (``start_ticker``) that polls on
    real time; a hung main thread then still produces the stack dump and
    the incident record even though nothing can raise into it.
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 deadlines: Optional[Dict[str, float]] = None,
                 on_expire: Optional[Callable[[PhaseTimeout], None]] = None,
                 dump: bool = True):
        self._clock = clock
        self._deadlines = dict(deadlines or {})
        self._on_expire = on_expire
        self._dump = dump
        self._active: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.expired: List[PhaseTimeout] = []

    def deadline_for(self, phase: str) -> Optional[float]:
        """Explicit per-instance deadline, else the phase's flag, else
        None (unwatched)."""
        if phase in self._deadlines:
            d = self._deadlines[phase]
            return float(d) if d and d > 0 else None
        flag_name = PHASES.get(phase)
        if flag_name is None:
            return None
        from ..core.flags import flag
        d = float(flag(flag_name))
        return d if d > 0 else None

    def begin(self, phase: str, deadline_s: Optional[float] = None):
        d = deadline_s if deadline_s is not None else self.deadline_for(phase)
        with self._lock:
            self._active[phase] = {"start": self._clock(),
                                   "deadline": d, "expired": False}

    def end(self, phase: str) -> float:
        with self._lock:
            info = self._active.pop(phase, None)
        if info is None:
            return 0.0
        return self._clock() - info["start"]

    def active_phases(self) -> List[str]:
        with self._lock:
            return list(self._active)

    @contextmanager
    def phase(self, name: str, deadline_s: Optional[float] = None):
        """Scope a phase; expiry enforcement comes from ``poll()`` (same
        thread between steps, or the ticker thread during a hang)."""
        self.begin(name, deadline_s)
        try:
            yield self
        finally:
            self.end(name)

    def poll(self, raise_on_expire: bool = True) -> List[PhaseTimeout]:
        """Expire every active phase past its deadline. Each phase
        expires at most once (the ticker would otherwise dump stacks
        every tick while the hang persists)."""
        now = self._clock()
        newly: List[PhaseTimeout] = []
        with self._lock:
            for phase, info in self._active.items():
                d = info["deadline"]
                if d is None or info["expired"]:
                    continue
                elapsed = now - info["start"]
                if elapsed > d:
                    info["expired"] = True
                    newly.append(PhaseTimeout(phase, elapsed, d))
        for exc in newly:
            self.expired.append(exc)
            if self._dump:
                _dump_all_threads(str(exc))
            _expired_metric(exc.phase)
            record_incident("watchdog_expired", phase=exc.phase,
                            elapsed_s=round(exc.elapsed_s, 3),
                            deadline_s=exc.deadline_s)
            if self._on_expire is not None:
                try:
                    self._on_expire(exc)
                except Exception:  # tpu-lint: disable=except-pass
                    pass
        if newly and raise_on_expire:
            raise newly[0]
        return newly

    # -- production ticker ---------------------------------------------------

    def start_ticker(self, interval_s: float = 1.0):
        """Poll on a daemon thread so a hung main thread still produces
        the dump/metric/incident (it cannot be *raised* into — exit
        conversion is HealthMonitor's job)."""
        if self._ticker is not None and self._ticker.is_alive():
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll(raise_on_expire=False)
                # the ticker must survive any poll-side error (metrics,
                # stderr) — it is the last line of hang diagnostics
                except Exception:  # tpu-lint: disable=except-pass
                    pass

        self._ticker = threading.Thread(
            target=_loop, name="ptq-watchdog", daemon=True)
        self._ticker.start()

    def stop_ticker(self):
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5.0)
            self._ticker = None


# -- process-global watchdog (flag-gated wiring for framework sites) ---------

_GLOBAL: Optional[Watchdog] = None
_GLOBAL_LOCK = threading.Lock()


def global_watchdog() -> Watchdog:
    """Lazily-created shared instance with the 1s ticker armed, used by
    the framework's phase sites (checkpoint commit, compile). The ticker
    produces the dump/metric/incident even when the phase's own thread
    is the hung one."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Watchdog()
            _GLOBAL.start_ticker(interval_s=1.0)
        return _GLOBAL


@contextmanager
def phase(name: str, deadline_s: Optional[float] = None):
    """Framework phase hook: no-op (one flag lookup) unless
    FLAGS_tpu_watchdog is on."""
    from ..core.flags import flag
    if not flag("FLAGS_tpu_watchdog"):
        yield
        return
    wd = global_watchdog()
    with wd.phase(name, deadline_s):
        yield


def run_with_deadline(fn: Callable[[], Any], window_s: float, *,
                      phase: str = "deadline", dump: bool = True):
    """Run ``fn()`` on a daemon thread with a wall-clock budget.

    Returns ``fn``'s value; re-raises its exception. If the budget
    expires first: all-thread stack dump + ``watchdog_expired_total``
    + incident record, then :class:`PhaseTimeout`. The worker thread is
    abandoned (daemon) — by construction it is hung on something
    uninterruptible, which is exactly why the caller needs its control
    flow back.
    """
    box: Dict[str, Any] = {}
    done = threading.Event()

    def _work():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            box["exc"] = e
        finally:
            done.set()

    th = threading.Thread(target=_work, name=f"ptq-deadline-{phase}",
                          daemon=True)
    th.start()
    if not done.wait(window_s):
        exc = PhaseTimeout(phase, window_s, window_s,
                           detail="still running at deadline")
        if dump:
            _dump_all_threads(str(exc))
        _expired_metric(phase)
        record_incident("watchdog_expired", phase=phase,
                        elapsed_s=window_s, deadline_s=window_s,
                        detail="run_with_deadline")
        raise exc
    if "exc" in box:
        raise box["exc"]
    return box["value"]


def init_with_retries(probe_fn, window_s: float = 240.0,
                      base_delay: float = 5.0, factor: float = 2.0,
                      max_delay: float = 60.0, log=None,
                      sleep=time.sleep, clock=time.monotonic,
                      phase: str = "device_init"):
    """Retry transient init failures with exponential backoff until the
    ``window_s`` budget expires.

    A dead backend fails two ways: ``probe_fn`` raises (claim refused —
    often transient while another job releases the chip, so retry), or
    it never returns (make_c_api_client hang). Each attempt runs on its
    own daemon thread so a hang is bounded by the remaining window
    instead of blocking forever; a hung attempt is NOT retried, because
    the runtime's init lock would block every later attempt behind it.

    Returns ``(ok, attempts, last_error)``. Injectable sleep/clock keep
    the backoff schedule unit-testable without real waiting.
    """
    deadline = clock() + window_s
    delay = base_delay
    attempts = 0
    last_err = "no attempt made"
    while clock() < deadline:
        attempts += 1
        box: Dict[str, Any] = {}
        done = threading.Event()

        def _attempt():
            try:
                probe_fn()
                box["ok"] = True
            except Exception as e:  # noqa: BLE001 — classified below
                box["err"] = str(e) or repr(e)
            finally:
                done.set()

        th = threading.Thread(target=_attempt, daemon=True)
        th.start()
        finished = done.wait(max(0.0, deadline - clock()))
        if box.get("ok"):
            return True, attempts, None
        if not finished:
            _expired_metric(phase)
            record_incident("watchdog_expired", phase=phase,
                            elapsed_s=window_s, deadline_s=window_s,
                            detail=f"init attempt {attempts} hung")
            return False, attempts, (
                f"attempt {attempts} hung past the {window_s:.0f}s window")
        last_err = box.get("err", "unknown init failure")
        pause = min(delay, max(0.0, deadline - clock()))
        if pause <= 0:
            break
        if log:
            log(f"device init attempt {attempts} failed ({last_err}); "
                f"retrying in {pause:.1f}s")
        sleep(pause)
        delay = min(delay * factor, max_delay)
    record_incident("init_failed", phase=phase, attempts=attempts,
                    window_s=window_s, error=str(last_err)[-500:])
    return False, attempts, last_err
