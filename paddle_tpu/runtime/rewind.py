"""Anomaly rewind: roll a diverging run back to the last good commit.

Reference analog: production LLM training playbooks (and the
Gemma-on-Cloud-TPU report in PAPERS.md) treat loss spikes and NaN
batches as routine events to be *recovered from*, not post-mortemed —
the standard manual remedy is "restore the last checkpoint and skip the
offending data window". :class:`RewindGuard` automates exactly that
loop on top of the crash-consistent checkpoint layer:

* **detect** — a non-finite loss (the numerics watchdog's territory —
  ``profiler.numerics`` supplies blame when enabled) or a spike above
  ``spike_factor`` x the recent healthy median;
* **rewind** — restore the newest committed step through the
  :class:`~..distributed.fault_tolerance.CheckpointManager` (which
  pins it as the keep-anchor and replays sampler/RNG state from the
  manifest);
* **skip** — advance the attached data pipeline past the whole window
  of batches consumed since that checkpoint (+ ``skip_extra``), so the
  relaunch does not re-eat the batch that poisoned the run;
* **account** — a structured ``anomaly_rewind`` incident in the runtime
  health buffer, plus ``rewind_total`` / ``rewind_skipped_batches_total``
  metrics;
* **bound** — at most ``max_rewinds`` rewinds per guard: a persistent
  divergence raises :class:`RewindBudgetExceeded` instead of
  livelocking the job.

Typical loop::

    guard = RewindGuard(mgr, data=loader, max_rewinds=2)
    state, start = mgr.restore(target)
    for step, batch in stepper:
        state, loss = train_step(state, batch)
        rw = guard.check(step, loss)
        if rw is not None:          # rolled back; batches already skipped
            state, step = rw.state, rw.step
"""
from __future__ import annotations

import math
from collections import deque
from typing import Any, Optional

from .watchdog import record_incident

__all__ = ["RewindBudgetExceeded", "RewindResult", "RewindGuard"]


class RewindBudgetExceeded(RuntimeError):
    """The rewind budget is spent and the loss is still diverging —
    fail loudly: this is a real bug (data, numerics, or hardware), not
    a transient to paper over."""


class RewindResult:
    """What a rewind produced: the restored ``state``, the ``step`` it
    resumes from, and the batch window that was skipped."""

    __slots__ = ("state", "step", "anomaly_step", "skipped_batches",
                 "reason")

    def __init__(self, state, step, anomaly_step, skipped_batches, reason):
        self.state = state
        self.step = int(step)
        self.anomaly_step = int(anomaly_step)
        self.skipped_batches = int(skipped_batches)
        self.reason = reason

    def __repr__(self):
        return (f"RewindResult(step={self.step}, anomaly_step="
                f"{self.anomaly_step}, skipped_batches="
                f"{self.skipped_batches}, reason={self.reason!r})")


def _metrics():
    from ..profiler import metrics
    return metrics


class RewindGuard:
    """Training-loop guard: feed it ``(step, loss)`` every step; on an
    anomaly it restores the last committed checkpoint and skips the
    offending batch window, within a bounded budget.

    ``manager`` is a :class:`~..distributed.fault_tolerance.
    CheckpointManager`; ``data`` (anything with ``state_dict``/
    ``load_state_dict`` — the DataLoader or DistributedBatchSampler) is
    advanced past the skipped window. When the manager already has the
    loader attached (``attach_data``), restore first replays the
    manifest's cursor and the guard then advances it; passing ``data``
    here is still required so the guard knows *what* to advance.
    """

    def __init__(self, manager, *, data=None, max_rewinds: int = 2,
                 spike_factor: float = 10.0, window: int = 32,
                 min_history: int = 5, skip_extra: int = 0,
                 restore_target: Any = None,
                 allow_version_skew: bool = False):
        if max_rewinds < 0:
            raise ValueError("max_rewinds must be >= 0")
        self.manager = manager
        self.data = data
        self.max_rewinds = int(max_rewinds)
        self.spike_factor = float(spike_factor)
        self.skip_extra = int(skip_extra)
        self.min_history = int(min_history)
        self.restore_target = restore_target
        self.allow_version_skew = bool(allow_version_skew)
        self.rewinds = 0
        self._history: deque = deque(maxlen=int(window))

    # -- detection ----------------------------------------------------------
    def classify(self, loss) -> Optional[str]:
        """``None`` for a healthy loss, else ``"nonfinite"``/``"spike"``."""
        try:
            val = float(loss)
        except (TypeError, ValueError):
            return None
        if not math.isfinite(val):
            return "nonfinite"
        if len(self._history) >= self.min_history:
            ref = sorted(self._history)[len(self._history) // 2]
            if ref > 0 and val > self.spike_factor * ref:
                return "spike"
        return None

    # -- the guard ----------------------------------------------------------
    def check(self, step: int, loss) -> Optional[RewindResult]:
        """Healthy -> records the loss and returns None. Anomalous ->
        performs the rewind and returns a :class:`RewindResult` (or
        raises :class:`RewindBudgetExceeded` once the budget is spent)."""
        reason = self.classify(loss)
        if reason is None:
            self._history.append(float(loss))
            return None
        return self.rewind(step, loss=loss, reason=reason)

    def rewind(self, anomaly_step: int, *, loss=None,
               reason: str = "manual") -> RewindResult:
        """Roll back to the newest committed checkpoint and skip the
        batch window ``(restored_step, anomaly_step]`` (+ skip_extra)."""
        m = _metrics()
        if self.rewinds >= self.max_rewinds:
            record_incident("rewind_budget_exhausted",
                            step=int(anomaly_step), reason=reason,
                            rewinds=self.rewinds, budget=self.max_rewinds)
            raise RewindBudgetExceeded(
                f"loss anomaly ({reason}) at step {anomaly_step} but the "
                f"rewind budget ({self.max_rewinds}) is already spent — "
                f"the divergence is persistent; inspect the incident "
                f"buffer and the last checkpoints instead of rewinding "
                f"further")
        target = self.manager.latest_step()
        if target is None:
            record_incident("rewind_failed", step=int(anomaly_step),
                            reason=reason, error="no committed checkpoint")
            raise RewindBudgetExceeded(
                f"loss anomaly ({reason}) at step {anomaly_step} with NO "
                f"committed checkpoint to rewind to under "
                f"{self.manager.root}")
        state, restored = self.manager.restore(
            self.restore_target, step=target,
            allow_version_skew=self.allow_version_skew)
        nskip = max(0, int(anomaly_step) - int(restored)) + self.skip_extra
        if self.data is not None and nskip > 0:
            self._advance_data(nskip)
        self.rewinds += 1
        self._history.clear()
        try:
            loss_val = float(loss) if loss is not None else None
        except (TypeError, ValueError):
            loss_val = None
        record_incident(
            "anomaly_rewind", step=int(anomaly_step), reason=reason,
            restored_step=int(restored), skipped_batches=nskip,
            loss=repr(loss_val), rewinds=self.rewinds,
            budget=self.max_rewinds)
        if m.enabled():
            m.counter("rewind_total",
                      "Anomaly rewinds to the last committed checkpoint"
                      ).inc()
            m.counter("rewind_skipped_batches_total",
                      "Batches skipped past by anomaly rewinds"
                      ).inc(nskip)
        return RewindResult(state, restored, anomaly_step, nskip, reason)

    def _advance_data(self, nbatches: int):
        st = self.data.state_dict()
        gbs = int(st.get("global_batch_size", 1))
        st["offset"] = int(st.get("offset", 0)) + int(nbatches) * gbs
        self.data.load_state_dict(st)
