"""Runtime health layer: phase watchdogs, heartbeats, hang recovery.

Promotes bench.py's ad-hoc hang defenses into a shared subsystem
(ROADMAP items 3/4): `watchdog` holds the phase-deadline machinery and
deadline executors, `health` the cross-rank heartbeat/beacon failure
detector that converts hangs into exit-101 elastic relaunches.
"""
from __future__ import annotations

from typing import List

from . import watchdog, health, rewind  # noqa: F401
from .watchdog import (PhaseTimeout, Watchdog, run_with_deadline,  # noqa: F401
                       init_with_retries, incidents, last_incident,
                       record_incident, clear_incidents,
                       persist_incidents, incident_sidecar_path)
from .health import (CollectiveTimeout, HealthMonitor,  # noqa: F401
                     HeartbeatTracker, collective_beacon,
                     record_fused_fallback)
from .rewind import (RewindBudgetExceeded, RewindResult,  # noqa: F401
                     RewindGuard)

__all__ = ["watchdog", "health", "rewind", "PhaseTimeout", "Watchdog",
           "run_with_deadline", "init_with_retries", "incidents",
           "last_incident", "record_incident", "clear_incidents",
           "persist_incidents", "incident_sidecar_path",
           "CollectiveTimeout", "HealthMonitor", "HeartbeatTracker",
           "collective_beacon",
           "record_fused_fallback", "RewindBudgetExceeded", "RewindResult",
           "RewindGuard", "summary_lines"]


def summary_lines() -> List[str]:
    """The "Health" block of ``Profiler.summary_table()``: watchdog
    flag state, monitor state (when installed), and the tail of the
    incident buffer."""
    from ..core.flags import flag
    lines: List[str] = ["Health"]
    mon = health.get()
    if mon is None:
        state = "on" if flag("FLAGS_tpu_watchdog") else "off"
        lines.append(f"  monitor: not installed (FLAGS_tpu_watchdog "
                     f"{state})")
    else:
        lines.extend("  " + ln for ln in mon.summary_lines())
    recs = incidents()
    if not recs:
        lines.append("  incidents: none")
        return lines
    lines.append(f"  incidents: {len(recs)} (last {min(len(recs), 5)}):")
    for rec in recs[-5:]:
        extra = {k: v for k, v in rec.items()
                 if k not in ("kind", "time", "rank")}
        detail = ", ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        lines.append(f"    {rec['kind']} (rank {rec['rank']}"
                     + (f": {detail}" if detail else "") + ")")
    return lines
